"""Paged KV cache + disaggregated prefill/decode (ISSUE 10).

Acceptance: paged is the default and byte-identical to dense (greedy AND
sampled) with the decode step still compiling once across churn; prefix
admission aliases ref-counted pages with ZERO rewrites of shared pages;
preemption under allocator pressure completes every request byte-identically
(requeued ahead of fresh arrivals, never failed); a 1-prefill + 1-decode
fleet serves the PR 6 workload byte-identical to a single engine with
``req.prefilled``/``req.handoff`` events on each request's trace lane.
Property tests hammer the allocator/page-table invariants (no double-free,
shared pages never written in place, atomic alloc, fragmentation soak).
"""

import dataclasses
import random
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu import telemetry
from maggy_tpu.exceptions import BadArgumentsError
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.serve import (
    BlockAllocator,
    Engine,
    OutOfPagesError,
    PageTable,
    Request,
    SamplingParams,
    Scheduler,
)

CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
PAGE = 16  # engine default page size; 4 pages per max_seq_len row here
SYS = list(range(100, 133))  # 33-token system prompt: 2 full pages shared


@pytest.fixture(scope="module")
def params():
    model = Decoder(CFG)
    return unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )


def reference(params, prompt, max_new):
    decode_model = Decoder(dataclasses.replace(CFG, decode=True))
    buf = np.zeros((1, len(prompt) + max_new), np.int32)
    buf[0, : len(prompt)] = prompt
    out = generate_cached(
        decode_model, params, jnp.asarray(buf), jnp.asarray([len(prompt)])
    )
    return list(np.asarray(out)[0, len(prompt):])


def run_scheduler(params, jobs, timeout=90, **engine_kw):
    """Submit (prompt, SamplingParams) jobs, run to completion; returns
    (token streams in submit order, engine, scheduler stats)."""
    engine = Engine(CFG, params, **engine_kw)
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        reqs = [scheduler.submit(p, sp) for p, sp in jobs]
        deadline = time.time() + timeout
        while time.time() < deadline and any(
            r.state not in ("done", "failed", "cancelled", "expired")
            for r in reqs
        ):
            time.sleep(0.01)
        assert all(r.state == "done" for r in reqs), [
            (r.state, r.error) for r in reqs
        ]
        stats = scheduler.stats()
    finally:
        scheduler.stop()
    return [list(r.tokens) for r in reqs], engine, stats


def pool_leaf(cache, name="k"):
    """The (first) named cache pool leaf."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        if jax.tree_util.keystr(path).endswith(f"['{name}']"):
            return leaf
    raise AssertionError(f"no {name!r} leaf")


# ---------------------------------------------------------- allocator units


def test_allocator_alloc_free_refcount():
    a = BlockAllocator(num_pages=9, page_size=PAGE)
    assert a.pages_total == 8 and a.pages_free == 8
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got  # scratch page never allocated
    assert a.pages_free == 5 and all(a.refcount(p) == 1 for p in got)
    # aliasing: refcount 2, shows in pages_shared, release is two-step
    a.share(got[:2])
    assert a.pages_shared == 2 and a.refcount(got[0]) == 2
    assert a.release(got[:2]) == 0  # still referenced: nothing freed
    assert a.pages_shared == 0 and a.refcount(got[0]) == 1
    assert a.release(got) == 3
    assert a.pages_free == 8
    a.check_invariants()


def test_allocator_atomic_and_errors():
    a = BlockAllocator(num_pages=5, page_size=PAGE)  # 4 usable
    got = a.alloc(3)
    with pytest.raises(OutOfPagesError):
        a.alloc(2)  # only 1 free: all-or-nothing
    assert a.pages_free == 1, "failed alloc must not leak pages"
    with pytest.raises(ValueError, match="double free"):
        a.release([got[0], got[0], got[0]])  # refs 1 -> freed -> double
    with pytest.raises(ValueError, match="unallocated"):
        a.share([0])  # scratch page is never shareable
    free_page = a.alloc(1)[0]
    a.release([free_page])
    with pytest.raises(ValueError, match="unallocated"):
        a.share([free_page])
    a.check_invariants()


def test_page_table_mirror():
    a = BlockAllocator(num_pages=9, page_size=PAGE)
    t = PageTable(num_slots=2, max_pages=4)
    pages = a.alloc(2)
    t.assign(0, pages)
    assert list(t.table[0]) == pages + [0, 0]
    grown = a.alloc(1)[0]
    t.grow(0, grown)
    assert t.count(0) == 3 and t.pages(0) == pages + [grown]
    t.check_invariants(a)
    # clear zeroes the row (released rows' masked writes hit scratch)
    freed = t.clear(0)
    assert freed == pages + [grown] and not t.table[0].any()
    a.release(freed)
    t.check_invariants(a)
    a.check_invariants()


@pytest.mark.slow
def test_allocator_fragmentation_soak():
    """Random alloc/share/release churn never breaks the invariants and
    never strands a page (free + referenced == total throughout)."""
    rng = random.Random(0)
    a = BlockAllocator(num_pages=33, page_size=8)
    held = []  # lists of (pages, aliased_from_held_index)
    for _ in range(2000):
        op = rng.random()
        if op < 0.45 and a.pages_free:
            n = rng.randint(1, min(4, a.pages_free))
            held.append(a.alloc(n))
        elif op < 0.6 and held:
            src = rng.choice(held)
            take = src[: rng.randint(1, len(src))]
            a.share(take)
            held.append(list(take))
        elif held:
            idx = rng.randrange(len(held))
            a.release(held.pop(idx))
        a.check_invariants()
    for pages in held:
        a.release(pages)
    assert a.pages_free == a.pages_total
    a.check_invariants()


# ------------------------------------------------------------- byte parity


def test_paged_is_default_and_matches_dense(params):
    """ACCEPTANCE: the paged path is the default, byte-identical to dense
    for greedy AND sampled streams under staggered churn, and the decode
    step compiles exactly once."""
    assert Engine(CFG, params).paged, "paged must be the default"
    for temp in (0.0, 0.8):
        jobs = [
            (p, SamplingParams(max_new=4 + i % 3, temperature=temp, seed=11 + i))
            for i, p in enumerate(
                [[1, 2, 3, 4], [5, 6, 7], [9, 10, 11, 12, 13], [2, 4, 6], [7, 3]]
            )
        ]
        dense, _, _ = run_scheduler(params, jobs, num_slots=3, paged=False)
        paged, engine, stats = run_scheduler(
            params, jobs, num_slots=3, paged=True
        )
        assert dense == paged, f"temp={temp}: paged diverges from dense"
        assert engine.compile_counts["decode"] == 1, engine.compile_counts
        if temp == 0.0:
            for (prompt, sp), stream in zip(jobs, paged):
                assert stream == reference(params, prompt, sp.max_new)
    # all pages returned once the wave drained
    assert engine.allocator.pages_free == engine.allocator.pages_total
    assert stats["paging"]["paged"] is True


def test_prefix_alias_shares_pages_zero_copy(params):
    """ACCEPTANCE: prefix admission on a shared-system-prompt workload
    aliases the shared FULL pages — refcount > 1, ``pages_shared`` > 0,
    and the pool bytes at the aliased pages are bit-for-bit untouched
    (zero KV row copies) — while outputs stay byte-identical."""
    engine = Engine(CFG, params, num_slots=4, paged=True)
    s0, _ = engine.admit(
        Request(prompt=SYS + [1, 2], params=SamplingParams(max_new=4))
    )
    anchor_pages = engine.page_table.pages(s0)
    shared_full = anchor_pages[: len(SYS) // PAGE]
    assert len(shared_full) == 2
    before_k = np.asarray(pool_leaf(engine.cache)[:, shared_full])
    before_v = np.asarray(pool_leaf(engine.cache, "v")[:, shared_full])

    s1, first = engine.admit(
        Request(prompt=SYS + [7, 8, 9], params=SamplingParams(max_new=4))
    )
    assert engine.prefix_hits == 1
    assert engine.pages_aliased == 2
    assert engine.page_table.pages(s1)[:2] == shared_full
    assert all(engine.allocator.refcount(p) == 2 for p in shared_full)
    assert engine.allocator.pages_shared == 2
    assert np.array_equal(
        before_k, np.asarray(pool_leaf(engine.cache)[:, shared_full])
    ), "shared K pages were rewritten (copy-on-write violated)"
    assert np.array_equal(
        before_v, np.asarray(pool_leaf(engine.cache, "v")[:, shared_full])
    )

    # the aliased request decodes byte-identically to a fresh reference
    stream = [first]
    while len(stream) < 4:
        out = engine.step()
        if s1 in out.tokens:
            stream.append(out.tokens[s1])
    assert stream == reference(params, SYS + [7, 8, 9], 4)

    # releasing the ANCHOR keeps the shared pages alive for the sharer;
    # releasing the sharer finally frees them
    engine.release(s0)
    assert all(engine.allocator.refcount(p) == 1 for p in shared_full)
    engine.release(s1)
    engine.flush()
    assert engine.allocator.pages_free == engine.allocator.pages_total
    engine.allocator.check_invariants()
    engine.page_table.check_invariants(engine.allocator)


# -------------------------------------------------------------- preemption


def test_preemption_completes_byte_identical(params):
    """ACCEPTANCE (chaos): a pool too small for the offered load preempts
    the youngest request instead of refusing/failing — every request
    completes, streams are byte-identical to an unpressured run, and no
    page leaks."""
    # 14-token prompts fit one page; max_new=12 grows each row to 2 pages
    # mid-decode: 3 rows x 2 pages > 5 usable pages -> growth must preempt
    jobs = [
        (list(range(1 + i, 15 + i)),
         SamplingParams(max_new=12, temperature=0.7, seed=i))
        for i in range(3)
    ]
    tel = telemetry.Telemetry(worker="preempt-test")
    engine = Engine(
        CFG, params, num_slots=3, paged=True, num_pages=6,
        telemetry_recorder=tel,
    )
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        reqs = [scheduler.submit(p, sp) for p, sp in jobs]
        deadline = time.time() + 90
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in reqs
        ):
            time.sleep(0.01)
        assert all(r.state == "done" for r in reqs), [
            (r.state, r.error) for r in reqs
        ]
        tight = [list(r.tokens) for r in reqs]
        preemptions = scheduler.preemptions
        stats = scheduler.stats()
    finally:
        scheduler.stop()
    assert preemptions >= 1, "pressure did not preempt"
    assert stats["preemptions"] == preemptions
    free, _, _ = run_scheduler(params, jobs, num_slots=3, paged=True)
    assert tight == free, "preemption changed token streams"
    assert engine.allocator.pages_free == engine.allocator.pages_total
    engine.allocator.check_invariants()
    # observability: the counter and the lifecycle event both fired
    snap = tel.snapshot()
    assert snap["counters"].get("serve.preemptions") == preemptions
    names = [e["name"] for e in tel.drain_events()]
    assert "req.preempted" in names


def test_pool_backpressure_and_impossible_request(params):
    """Memory pressure never FAILS a request: admission backpressures until
    pages free up. Only a request that could NEVER fit fails, at submit."""
    engine = Engine(CFG, params, num_slots=4, paged=True, num_pages=4)
    scheduler = Scheduler(engine)
    # 3 usable pages total: a 40-token prompt + 24 new needs 4 -> impossible
    with pytest.raises(BadArgumentsError, match="pages"):
        scheduler.submit(list(range(1, 41)), SamplingParams(max_new=24))
    scheduler.start()
    try:
        # each needs 2 pages; only one fits at a time beside another
        reqs = [
            scheduler.submit(
                list(range(10 * i + 1, 10 * i + 20)), SamplingParams(max_new=8)
            )
            for i in range(4)
        ]
        deadline = time.time() + 90
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in reqs
        ):
            time.sleep(0.01)
        assert all(r.state == "done" for r in reqs), [
            (r.state, r.error) for r in reqs
        ]
    finally:
        scheduler.stop()
    assert engine.allocator.pages_free == engine.allocator.pages_total


def test_max_pages_per_req_knob(params):
    """The live ``serve.max_pages_per_req`` cap rejects oversized requests
    at submit and is applied through the autopilot target seam."""
    from maggy_tpu.autopilot.controller import SchedulerTarget

    engine = Engine(CFG, params, num_slots=2, paged=True)
    scheduler = Scheduler(engine)
    target = SchedulerTarget(scheduler)
    cur = target.current()
    assert cur["serve.page_size"] == engine.page_size
    assert cur["serve.max_pages_per_req"] == engine.pages_per_row
    assert target.apply("serve.max_pages_per_req", 1)
    assert engine.max_pages_per_req == 1
    with pytest.raises(BadArgumentsError, match="max_pages_per_req"):
        scheduler.submit(list(range(1, 15)), SamplingParams(max_new=10))
    scheduler.submit(list(range(1, 9)), SamplingParams(max_new=4))  # 12 tok: fits


def test_planner_shrinks_pages_before_slots():
    """Satellite: the memory-bound serve playbook shrinks pages-per-request
    BEFORE shrinking num_slots."""
    from maggy_tpu.autopilot.diagnose import Diagnosis
    from maggy_tpu.autopilot.plan import Planner

    diag = Diagnosis(
        bottleneck="memory_bound", scope="serve", evidence={}, shares={},
        reason="test",
    )
    moves = Planner().plan(
        diag,
        {"serve.num_slots": 8, "serve.max_pages_per_req": 4},
    )
    assert [m.knob for m in moves] == [
        "serve.max_pages_per_req",
        "serve.num_slots",
    ]
    assert moves[0].value == 2 and moves[1].value == 4


# ---------------------------------------------------- concurrency at budget


def test_concurrency_doubles_at_fixed_page_budget(params):
    """At an equal simulated HBM budget (dense_slots full rows' worth of
    pages), the paged engine admits >= 2x the dense slot count of
    typical-length requests concurrently."""
    dense_slots = 2
    budget = dense_slots * (CFG.max_seq_len // PAGE)  # 8 pages
    engine = Engine(
        CFG, params, num_slots=16, paged=True, num_pages=budget + 1
    )
    resident = 0
    # 12-token requests (1 page now, 2 worst-case) admit until pages run out
    for i in range(16):
        try:
            engine.admit(
                Request(
                    prompt=[1 + i, 2, 3, 4],
                    params=SamplingParams(max_new=8),
                )
            )
            resident += 1
        except OutOfPagesError:
            break
    assert resident >= 2 * dense_slots, (resident, dense_slots)


# ------------------------------------------------------------ reconfigure


def test_reconfigure_rebuilds_paged_pool(params):
    """Drain-and-reconfigure on a paged engine rebuilds the allocator and
    pool at the new geometry and still decodes byte-identically."""
    engine = Engine(CFG, params, num_slots=2, paged=True)
    engine.reconfigure(4)
    assert engine.slots.num_slots == 4
    assert engine.allocator.pages_total == 4 * engine.pages_per_row
    slot, first = engine.admit(
        Request(prompt=[1, 2, 3], params=SamplingParams(max_new=4))
    )
    stream = [first]
    while len(stream) < 4:
        out = engine.step()
        if slot in out.tokens:
            stream.append(out.tokens[slot])
    assert stream == reference(params, [1, 2, 3], 4)


# --------------------------------------------------- disaggregated serving


def test_disaggregated_fleet_byte_identical(params):
    """ACCEPTANCE: a 2-replica disaggregated fleet (1 prefill + 1 decode)
    serves the PR 6 workload byte-identical to a single engine, with
    ``req.prefilled`` and ``req.handoff`` events visible on each request's
    trace lane and the handoff latency in the histogram registry."""
    from maggy_tpu.monitor import render_status
    from maggy_tpu.serve import ServeClient
    from maggy_tpu.serve.fleet import ReplicaSpec, launch_fleet

    prompts = [
        [1, 2, 3, 4], [5, 6, 7], [9, 10, 11, 12, 13],
        [2, 4, 6, 8, 10, 12], [7, 3], [40, 41, 42],
        [1, 2, 3, 4, 5], [6, 5, 4],
    ]
    tel = telemetry.Telemetry(worker="router-test")
    spec = ReplicaSpec(CFG, params, num_slots=4)
    router = launch_fleet(
        spec, replicas=1, prefill_replicas=1, secret="s",
        telemetry_recorder=tel,
    )
    host, port = router.start(host="127.0.0.1")
    client = ServeClient(("127.0.0.1", port), "s")
    try:
        ids = [
            client.submit(p, max_new=6, seed=i)
            for i, p in enumerate(prompts)
        ]
        streams, traces = [], []
        for rid in ids:
            deadline = time.time() + 90
            snap = None
            while time.time() < deadline:
                snap = client.poll(rid)
                if snap.get("done"):
                    break
                time.sleep(0.02)
            assert snap and snap.get("state") == "done", snap
            streams.append(snap["tokens"])
            traces.append(snap["trace"])
        stats = client.stats()
        status = client._call({"type": "STATUS"})
    finally:
        client.close()
        router.stop()

    jobs = [(p, SamplingParams(max_new=6, seed=i)) for i, p in enumerate(prompts)]
    single, _, _ = run_scheduler(params, jobs, num_slots=4)
    assert streams == single, "disaggregated fleet diverges from one engine"

    assert stats["routing"]["prefilled"] == len(prompts)
    assert stats["routing"]["handoffs"] == len(prompts)
    # every request's trace lane carries the prefill + handoff milestones
    events = tel.drain_events()
    for trace in traces:
        lane = {e["name"] for e in events if e.get("trace") == trace}
        assert "req.prefilled" in lane and "req.handoff" in lane, lane
    # handoff latency reaches the histogram + gauge surfaces
    snap = tel.snapshot()
    assert "serve.handoff_ms" in snap.get("hist", {})
    assert "serve.handoff_ms" in snap.get("gauges", {})
    # fleet panel renders roles and handoff counters
    panel = render_status(status)
    assert "prefill" in panel and "handoffs=" in panel, panel


def test_prefill_worker_fallback(params):
    """A dead prefill replica degrades to plain dispatch — requests still
    complete (the decode replica prefills for itself)."""
    from maggy_tpu.serve import ServeClient
    from maggy_tpu.serve.fleet import ReplicaSpec, launch_fleet

    spec = ReplicaSpec(CFG, params, num_slots=4)
    router = launch_fleet(spec, replicas=1, prefill_replicas=1, secret="s")
    host, port = router.start(host="127.0.0.1")
    client = ServeClient(("127.0.0.1", port), "s")
    try:
        # kill the prefill replica (the last one built)
        prefill_replica = router.prefill_workers[0].replica
        prefill_replica.kill()
        rid = client.submit([1, 2, 3, 4], max_new=4)
        deadline = time.time() + 60
        snap = None
        while time.time() < deadline:
            snap = client.poll(rid)
            if snap.get("done"):
                break
            time.sleep(0.02)
        assert snap and snap["state"] == "done", snap
        assert snap["tokens"] == reference(params, [1, 2, 3, 4], 4)
    finally:
        client.close()
        router.stop()


# ------------------------------------------------------------- panel/stats


def test_paging_stats_and_serve_panel(params):
    """`paging` in scheduler stats and the pages line on the serve panel."""
    from maggy_tpu.monitor import render_status

    engine = Engine(CFG, params, num_slots=2, paged=True)
    scheduler = Scheduler(engine)
    stats = scheduler.stats()
    paging = stats["paging"]
    assert paging["paged"] and paging["page_size"] == PAGE
    assert paging["pages_free"] == paging["pages_total"]
    status = {
        "name": "t", "kind": "serve", "state": "serving",
        "app_id": "t", "run_id": 0, "serve": stats,
    }
    panel = render_status(status)
    assert "pages" in panel, panel
