"""Pipeline parallelism: GPipe schedule correctness vs sequential execution,
gradient flow, and validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.parallel.mesh import make_mesh
from maggy_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_grads_1f1b,
    stack_stage_params,
)
from maggy_tpu.parallel.spec import ShardingSpec


def make_problem(n_layers=8, d=16, n_micro=8, mb=4, seed=0):
    rng = jax.random.key(seed)
    kw, kx = jax.random.split(rng)
    # per-layer residual MLP: x + tanh(x @ W_l)
    weights = jax.random.normal(kw, (n_layers, d, d)) * 0.3
    x = jax.random.normal(kx, (n_micro, mb, d))

    def layer(w, x):
        return x + jnp.tanh(x @ w)

    def stage_fn(stage_w, x):  # stage_w: [layers_per_stage, d, d]
        def body(x, w):
            return layer(w, x), None

        out, _ = jax.lax.scan(body, x, stage_w)
        return out

    def sequential(x_all):
        def full(x):
            for l in range(n_layers):
                x = layer(weights[l], x)
            return x

        return jax.vmap(full)(x_all)

    return weights, x, stage_fn, sequential


@pytest.mark.parametrize("n_stages", [2, 4])
def test_pipeline_matches_sequential(n_stages):
    weights, x, stage_fn, sequential = make_problem()
    mesh = make_mesh(ShardingSpec(pp=n_stages, dp=8 // n_stages))
    stage_w = stack_stage_params(weights, n_stages)
    with mesh:
        out = pipeline_apply(stage_fn, stage_w, x, mesh=mesh)
    ref = sequential(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_single_stage_path():
    weights, x, stage_fn, sequential = make_problem(n_layers=4)
    mesh = make_mesh(ShardingSpec(dp=8))
    stage_w = stack_stage_params(weights, 1)
    with mesh:
        out = pipeline_apply(stage_fn, stage_w, x, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(sequential(x)), atol=1e-5)


def test_pipeline_gradients_match():
    weights, x, stage_fn, sequential = make_problem(n_layers=4, n_micro=4)
    mesh = make_mesh(ShardingSpec(pp=4, dp=2))
    stage_w = stack_stage_params(weights, 4)

    def loss_pipe(w):
        with mesh:
            return pipeline_apply(stage_fn, w, x, mesh=mesh).sum()

    def loss_seq(w_flat):
        def full(xx):
            for l in range(4):
                xx = xx + jnp.tanh(xx @ w_flat[l])
            return xx

        return jax.vmap(full)(x).sum()

    g_pipe = jax.grad(loss_pipe)(stage_w)
    g_seq = jax.grad(loss_seq)(weights)
    np.testing.assert_allclose(
        np.asarray(g_pipe.reshape(4, 16, 16)), np.asarray(g_seq), atol=1e-4
    )


def test_pipeline_scatter_output_matches_replicated():
    """out_mode='scatter' reduce-scatters the micro axis over stages instead
    of all-reducing the full buffer; reassembled, it is the same tensor."""
    weights, x, stage_fn, sequential = make_problem()
    mesh = make_mesh(ShardingSpec(pp=4, dp=2))
    stage_w = stack_stage_params(weights, 4)
    with mesh:
        rep = pipeline_apply(stage_fn, stage_w, x, mesh=mesh)
        scat = pipeline_apply(stage_fn, stage_w, x, mesh=mesh, out_mode="scatter")
    np.testing.assert_allclose(np.asarray(scat), np.asarray(rep), atol=1e-5)
    with pytest.raises(ValueError, match="divisible"):
        with mesh:
            pipeline_apply(
                stage_fn, stage_w, x[:6], mesh=mesh, out_mode="scatter"
            )


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 8), (4, 5)])
@pytest.mark.slow
def test_1f1b_matches_gpipe_autodiff(n_stages, n_micro):
    """The explicit 1F1B schedule produces the same loss and parameter grads
    as jax.grad through the GPipe schedule (and hence as the sequential
    model), for even and ragged micro/stage ratios."""
    weights, x, stage_fn, _ = make_problem(n_micro=n_micro)
    mesh = make_mesh(ShardingSpec(pp=n_stages, dp=8 // n_stages))
    stage_w = stack_stage_params(weights, n_stages)
    rng = jax.random.key(42)
    targets = jax.random.normal(rng, x.shape)

    def loss_fn(p, y, t):
        return ((y - t) ** 2).mean()

    def gpipe_loss(w):
        with mesh:
            outs = pipeline_apply(stage_fn, w, x, mesh=mesh)
        return jax.vmap(lambda y, t: ((y - t) ** 2).mean())(outs, targets).mean()

    ref_loss, ref_grads = jax.value_and_grad(gpipe_loss)(stage_w)

    with mesh:
        loss, grads = pipeline_grads_1f1b(
            stage_fn, loss_fn, stage_w, x, targets, mesh=mesh
        )
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads), np.asarray(ref_grads), atol=2e-5
    )


def test_1f1b_single_stage_path():
    weights, x, stage_fn, _ = make_problem(n_layers=4, n_micro=4)
    mesh = make_mesh(ShardingSpec(dp=8))
    stage_w = stack_stage_params(weights, 1)
    targets = jnp.zeros_like(x)

    def loss_fn(p, y, t):
        return ((y - t) ** 2).mean()

    with mesh:
        loss, grads = pipeline_grads_1f1b(
            stage_fn, loss_fn, stage_w, x, targets, mesh=mesh
        )
    assert np.isfinite(float(loss))
    assert grads.shape == stage_w.shape


def test_pipeline_validation():
    weights, x, stage_fn, _ = make_problem(n_layers=8, n_micro=2)
    mesh = make_mesh(ShardingSpec(pp=4, dp=2))
    stage_w = stack_stage_params(weights, 4)
    with pytest.raises(ValueError, match="microbatches"):
        with mesh:
            pipeline_apply(stage_fn, stage_w, x, mesh=mesh)  # 2 micro < 4 stages
    with pytest.raises(ValueError, match="divisible"):
        stack_stage_params(weights, 3)
