"""Pipeline parallelism: GPipe schedule correctness vs sequential execution,
gradient flow, and validation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.parallel.mesh import make_mesh
from maggy_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from maggy_tpu.parallel.spec import ShardingSpec


def make_problem(n_layers=8, d=16, n_micro=8, mb=4, seed=0):
    rng = jax.random.key(seed)
    kw, kx = jax.random.split(rng)
    # per-layer residual MLP: x + tanh(x @ W_l)
    weights = jax.random.normal(kw, (n_layers, d, d)) * 0.3
    x = jax.random.normal(kx, (n_micro, mb, d))

    def layer(w, x):
        return x + jnp.tanh(x @ w)

    def stage_fn(stage_w, x):  # stage_w: [layers_per_stage, d, d]
        def body(x, w):
            return layer(w, x), None

        out, _ = jax.lax.scan(body, x, stage_w)
        return out

    def sequential(x_all):
        def full(x):
            for l in range(n_layers):
                x = layer(weights[l], x)
            return x

        return jax.vmap(full)(x_all)

    return weights, x, stage_fn, sequential


@pytest.mark.parametrize("n_stages", [2, 4])
def test_pipeline_matches_sequential(n_stages):
    weights, x, stage_fn, sequential = make_problem()
    mesh = make_mesh(ShardingSpec(pp=n_stages, dp=8 // n_stages))
    stage_w = stack_stage_params(weights, n_stages)
    with mesh:
        out = pipeline_apply(stage_fn, stage_w, x, mesh=mesh)
    ref = sequential(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_single_stage_path():
    weights, x, stage_fn, sequential = make_problem(n_layers=4)
    mesh = make_mesh(ShardingSpec(dp=8))
    stage_w = stack_stage_params(weights, 1)
    with mesh:
        out = pipeline_apply(stage_fn, stage_w, x, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(sequential(x)), atol=1e-5)


def test_pipeline_gradients_match():
    weights, x, stage_fn, sequential = make_problem(n_layers=4, n_micro=4)
    mesh = make_mesh(ShardingSpec(pp=4, dp=2))
    stage_w = stack_stage_params(weights, 4)

    def loss_pipe(w):
        with mesh:
            return pipeline_apply(stage_fn, w, x, mesh=mesh).sum()

    def loss_seq(w_flat):
        def full(xx):
            for l in range(4):
                xx = xx + jnp.tanh(xx @ w_flat[l])
            return xx

        return jax.vmap(full)(x).sum()

    g_pipe = jax.grad(loss_pipe)(stage_w)
    g_seq = jax.grad(loss_seq)(weights)
    np.testing.assert_allclose(
        np.asarray(g_pipe.reshape(4, 16, 16)), np.asarray(g_seq), atol=1e-4
    )


def test_pipeline_validation():
    weights, x, stage_fn, _ = make_problem(n_layers=8, n_micro=2)
    mesh = make_mesh(ShardingSpec(pp=4, dp=2))
    stage_w = stack_stage_params(weights, 4)
    with pytest.raises(ValueError, match="microbatches"):
        with mesh:
            pipeline_apply(stage_fn, stage_w, x, mesh=mesh)  # 2 micro < 4 stages
    with pytest.raises(ValueError, match="divisible"):
        stack_stage_params(weights, 3)
