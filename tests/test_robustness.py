"""Protocol/serialization robustness: malformed frames, oversized frames,
garbage JSON, trial round-trip fuzz, monitor CLI."""

import json
import socket
import struct

import numpy as np
import pytest

from maggy_tpu import Searchspace, Trial
from maggy_tpu.core import rpc

pytestmark = pytest.mark.slow  # subprocess/multi-process tier


@pytest.fixture()
def server():
    s = rpc.Server(num_executors=1)
    s.register_callback("PING", lambda m: {"type": "PING"})
    s.start(host="127.0.0.1")
    yield s
    s.stop()


def raw_socket(server):
    return socket.create_connection((server.host, server.port), timeout=5)


def test_garbage_bytes_do_not_kill_server(server):
    sock = raw_socket(server)
    sock.sendall(b"\x00\x00\x00\x05nojso")  # length frame, invalid JSON
    sock.close()
    # server still answers a well-formed client
    c = rpc.Client((server.host, server.port), 0, server.secret)
    assert c._request({"type": "PING"})["type"] == "PING"
    c.stop()


def test_oversized_frame_disconnects_cleanly(server):
    sock = raw_socket(server)
    sock.sendall(struct.pack(">I", 1 << 30))  # announces a 1 GiB frame
    # server must drop the connection without allocating
    sock.settimeout(5)
    assert sock.recv(4) == b""  # closed
    sock.close()
    c = rpc.Client((server.host, server.port), 0, server.secret)
    assert c._request({"type": "PING"})["type"] == "PING"
    c.stop()


def test_non_dict_payload(server):
    sock = raw_socket(server)
    payload = json.dumps([1, 2, 3]).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)
    sock.settimeout(5)
    # either an ERR reply or a clean disconnect — never a hang/crash
    try:
        header = sock.recv(4)
        if header:
            (length,) = struct.unpack(">I", header)
            reply = json.loads(sock.recv(length))
            assert reply["type"] == "ERR"
    except OSError:
        pass
    sock.close()
    c = rpc.Client((server.host, server.port), 0, server.secret)
    assert c._request({"type": "PING"})["type"] == "PING"
    c.stop()


def test_trial_roundtrip_fuzz():
    rng = np.random.default_rng(0)
    sp = Searchspace(
        a=("DOUBLE", [-10.0, 10.0]),
        b=("INTEGER", [-5, 5]),
        c=("CATEGORICAL", ["x", "y", "z"]),
    )
    for _ in range(50):
        t = Trial(sp.sample())
        for s in range(rng.integers(0, 5)):
            t.append_metric(float(rng.normal()), step=s)
        if rng.random() < 0.5:
            t.finalize(float(rng.normal()))
        t2 = Trial.from_json(t.to_json())
        assert t2.trial_id == t.trial_id
        assert t2.metric_history == t.metric_history
        assert t2.status == t.status


def test_monitor_cli_against_live_server(server, capsys):
    """monitor's one-shot poll path: drain a LOG reply and exit on server stop."""
    server.register_callback(
        "LOG", lambda m: {"type": "LOG", "logs": ["hello-from-driver"], "progress": "[=>] 1/2"}
    )
    import threading
    import time

    from maggy_tpu import monitor as monitor_mod

    t = threading.Thread(
        target=monitor_mod.monitor,
        args=(server.host, server.port, server.secret, 0.05),
        daemon=True,
    )
    t.start()
    time.sleep(0.5)
    server.stop()
    t.join(timeout=10)
    assert not t.is_alive()
    out = capsys.readouterr().out
    assert "hello-from-driver" in out
    assert "1/2" in out


def test_monitor_cli_arg_validation():
    from maggy_tpu.monitor import main

    with pytest.raises(SystemExit):
        main(["no-port", "secret"])
