"""Unified telemetry subsystem (ISSUE 1): recorder semantics, JSONL sink +
Chrome-trace export, Trainer.fit step metrics, the profiler hook, the STATUS
panel, the <1% overhead budget, and the no-bare-print lint."""

import json
import os
import socket
import threading
import time

import jax
import optax
import pytest

from maggy_tpu.telemetry import recorder as rec_mod
from maggy_tpu.telemetry.export import export_chrome_trace
from maggy_tpu.telemetry.recorder import NullTelemetry, Telemetry
from maggy_tpu.telemetry.sink import worker_telemetry


def _tiny_trainer(seed=0):
    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=seed)
    state = trainer.make_state(jax.random.key(0), next(data))
    return trainer, state, data


# ------------------------------------------------------------------- recorder


def test_recorder_spans_gauges_counters_rpc():
    tel = Telemetry(worker=7, role="trial")
    with tel.span("outer", step=3):
        time.sleep(0.002)
    tel.gauge("step_time_ms", 4.2)
    tel.gauge("step_time_ms", 5.0)  # gauges keep the latest value
    tel.count("trials_done")
    tel.rpc("GET", 1.0)
    tel.rpc("GET", 3.0)
    tel.rpc("METRIC", None, ok=False)

    snap = tel.snapshot()
    assert snap["worker"] == "7" and snap["role"] == "trial"
    assert snap["gauges"]["step_time_ms"] == 5.0
    assert snap["counters"]["trials_done"] == 1
    assert snap["counters"]["rpc_errors.METRIC"] == 1
    assert snap["rpc"]["GET"]["n"] == 2
    assert snap["rpc"]["GET"]["mean_ms"] == pytest.approx(2.0)
    assert snap["rpc"]["GET"]["max_ms"] == pytest.approx(3.0)

    events = tel.drain_events()
    span = next(e for e in events if e["kind"] == "span")
    assert span["name"] == "outer" and span["dur_ms"] >= 1.0
    assert span["attrs"] == {"step": 3}
    assert "ts" in span and "tid" in span
    assert not tel.drain_events()  # drained


def test_recorder_span_records_on_exception():
    tel = Telemetry(worker=0)
    with pytest.raises(ValueError):
        with tel.span("boom"):
            raise ValueError("x")
    events = tel.drain_events()
    assert events and events[0]["name"] == "boom"


def test_disabled_env_flag_returns_null(monkeypatch):
    monkeypatch.setenv("MAGGY_TPU_TELEMETRY", "0")
    assert not rec_mod.enabled()
    tel = rec_mod.get()
    assert isinstance(tel, NullTelemetry) and not tel.active
    with tel.span("x"):
        pass
    tel.gauge("g", 1.0)
    assert tel.snapshot() == {} and tel.drain_events() == []
    # sink factory also degrades to the shared null recorder
    assert isinstance(worker_telemetry(0, "/tmp/x"), NullTelemetry)


def test_thread_ambient_recorder():
    tel = Telemetry(worker=1)
    seen = {}

    def other_thread():
        seen["other"] = rec_mod.get()

    with rec_mod.current(tel):
        assert rec_mod.get() is tel
        t = threading.Thread(target=other_thread)
        t.start()
        t.join()
    # thread-local: another thread never sees this thread's recorder
    assert seen["other"] is not tel
    assert rec_mod.get() is not tel


# -------------------------------------------------------- sink + chrome trace


def test_sink_and_chrome_trace_export(tmp_env):
    exp_dir = tmp_env.experiment_dir("app_tel", 1)
    for pid in (0, 1):
        tel = worker_telemetry(pid, exp_dir, role="trial", env=tmp_env)
        with tel.span("trial", trial_id=f"t{pid}"):
            with tel.span("train_step", step=0):
                time.sleep(0.001)
        tel.gauge("step_time_ms", 2.5 + pid)
        tel.close()
        path = os.path.join(exp_dir, "telemetry", f"worker_{pid}.jsonl")
        assert os.path.exists(path)
        lines = [json.loads(l) for l in open(path) if l.strip()]
        kinds = {l["kind"] for l in lines}
        assert {"span", "gauge", "snapshot"} <= kinds

    out = export_chrome_trace(tmp_env, exp_dir)
    assert out and out.endswith("trace.json")
    trace = json.load(open(out))
    events = trace["traceEvents"]
    assert events
    # structural validity: required fields present, timestamps sorted
    for e in events:
        assert {"ph", "ts", "pid", "tid", "name"} <= set(e)
    xs = [e for e in events if e["ph"] == "X"]
    cs = [e for e in events if e["ph"] == "C"]
    assert xs and cs
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    assert {e["pid"] for e in xs} == {0, 1}


def test_chrome_trace_skips_torn_lines(tmp_env):
    exp_dir = tmp_env.experiment_dir("app_torn", 1)
    tdir = os.path.join(exp_dir, "telemetry")
    os.makedirs(tdir)
    with open(os.path.join(tdir, "worker_0.jsonl"), "w") as f:
        f.write(
            json.dumps(
                {"kind": "span", "name": "s", "ts": 1.0, "dur_ms": 2.0, "worker": "0"}
            )
            + "\n"
        )
        f.write('{"kind": "span", "name": "torn"')  # crashed-worker tail
    out = export_chrome_trace(tmp_env, exp_dir)
    trace = json.load(open(out))
    assert sum(e["ph"] == "X" for e in trace["traceEvents"]) == 1


# --------------------------------------------------------------- Trainer.fit


def test_fit_exposes_steps_per_sec_and_gauges():
    trainer, state, data = _tiny_trainer()
    tel = Telemetry(worker=0)
    with rec_mod.current(tel):
        state, metrics = trainer.fit(state, data, num_steps=4)
    assert metrics["steps_per_sec"] > 0
    g = tel.snapshot()["gauges"]
    assert g["compile_time_ms"] > 0
    assert g["step_time_ms"] > 0
    assert g["steps_per_sec"] == pytest.approx(metrics["steps_per_sec"])
    assert g["tokens_per_sec"] > 0  # LM batch: 8*32 tokens/step
    assert "mfu_est" not in g  # unknown peak FLOPs on the CPU mesh
    names = [e["name"] for e in tel.drain_events() if e["kind"] == "span"]
    assert names.count("train_step") == 4
    assert names.count("shard_batch") == 4


def test_fit_steps_per_sec_with_telemetry_disabled(monkeypatch):
    monkeypatch.setenv("MAGGY_TPU_TELEMETRY", "0")
    trainer, state, data = _tiny_trainer()
    state, metrics = trainer.fit(state, data, num_steps=2)
    # the metrics-dict contract holds even with the recorder off
    assert metrics["steps_per_sec"] > 0


# ------------------------------------------------------------- profiler hook


class _FakeProfiler:
    def __init__(self, counter):
        self.counter = counter  # shared data-iterator call counter
        self.starts = []
        self.stops = 0

    def start_trace(self, logdir):
        self.starts.append((logdir, self.counter["n"]))

    def stop_trace(self):
        self.stops += 1


def _counting(data, counter):
    for batch in data:
        counter["n"] += 1
        yield batch


def test_profiler_hook_starts_and_stops_at_bounds(monkeypatch, tmp_path):
    trainer, state, data = _tiny_trainer()
    counter = {"n": 0}
    fake = _FakeProfiler(counter)
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    # prefetch=0: the draw-count assertion below pins when batches leave the
    # iterator, which only the synchronous input path makes deterministic
    trainer.fit(
        state, _counting(data, counter), num_steps=6,
        profile_dir=str(tmp_path), profile_steps=(1, 3), prefetch=0,
    )
    # started before step profile_steps[0]'s batch was drawn...
    assert fake.starts == [(str(tmp_path), 1)]
    # ...and stopped exactly once, at profile_steps[1]
    assert fake.stops == 1


def test_profiler_finally_stops_active_trace_on_error(monkeypatch, tmp_path):
    trainer, state, data = _tiny_trainer()
    counter = {"n": 0}
    fake = _FakeProfiler(counter)
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)

    def exploding(data):
        for i, batch in enumerate(data):
            if i == 2:  # mid-capture: trace started at step 1, stops at 3
                raise RuntimeError("data loader died")
            counter["n"] += 1
            yield batch

    with pytest.raises(RuntimeError, match="data loader died"):
        trainer.fit(
            state, exploding(data), num_steps=6,
            profile_dir=str(tmp_path), profile_steps=(1, 3),
        )
    assert len(fake.starts) == 1
    assert fake.stops == 1  # the finally path closed the dangling trace


def test_profiler_not_started_without_profile_dir(monkeypatch):
    trainer, state, data = _tiny_trainer()
    fake = _FakeProfiler({"n": 0})
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    trainer.fit(state, data, num_steps=3)
    assert fake.starts == [] and fake.stops == 0


# ------------------------------------------------------------ overhead budget


def test_telemetry_overhead_within_budget():
    """The per-step recorder cost (what Trainer.fit adds: 2 spans + ~2
    gauges) must be far under the 1% step-time budget. Asserted loosely at
    5% against the real compiled step to stay robust to CI noise; bench.py
    records the precise A/B number each round."""
    trainer, state, data = _tiny_trainer()
    batch = trainer.shard_batch(next(data))
    state, m = trainer.step(state, batch)  # compile
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(5):
        state, m = trainer.step(state, batch)
    float(m["loss"])
    step_ms = (time.perf_counter() - t0) / 5 * 1e3

    tel = Telemetry(worker=0)
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        with tel.span("shard_batch", step=i):
            pass
        with tel.span("train_step", step=i):
            pass
        tel.gauge("step_time_ms", 1.0)
        tel.gauge("steps_per_sec", 1.0)
    cost_ms = (time.perf_counter() - t0) / n * 1e3
    assert cost_ms < step_ms * 0.05, (cost_ms, step_ms)

    # the disabled path must be cheaper still — it is pure no-op dispatch
    null = NullTelemetry()
    t0 = time.perf_counter()
    for i in range(n):
        with null.span("train_step", step=i):
            pass
        null.gauge("step_time_ms", 1.0)
    null_ms = (time.perf_counter() - t0) / n * 1e3
    assert null_ms < step_ms * 0.05, (null_ms, step_ms)


# ------------------------------------------------- e2e dryrun + STATUS panel


def test_distributed_dryrun_telemetry_e2e(tmp_env):
    """A distributed dryrun on the CPU mesh produces per-worker JSONL + a
    structurally valid merged Chrome trace, and STATUS carries the worker
    telemetry snapshots the monitor panel renders."""
    from maggy_tpu import experiment
    from maggy_tpu.config import DistributedConfig
    from maggy_tpu.core import rpc
    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.monitor import render_status
    from maggy_tpu.train.data import synthetic_lm_batches

    cfg = DecoderConfig.tiny()
    release = threading.Event()

    def train(model, dataset, hparams, reporter, ctx):
        trainer = ctx.trainer(model, optax.adamw(hparams["lr"]))
        state = trainer.make_state(jax.random.key(0), next(dataset))
        state, metrics = trainer.fit(state, dataset, num_steps=4)
        # hold until the main thread has read STATUS with telemetry attached
        release.wait(timeout=30)
        return {"metric": -metrics["loss"], **metrics}

    dconf = DistributedConfig(
        module=Decoder(cfg),
        dataset=synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=5),
        hparams={"lr": 1e-3},
        sharding="dp",
        hb_interval=0.05,
        name="telemetry-e2e",
    )
    holder = {}
    t = threading.Thread(target=lambda: holder.update(r=experiment.lagom(train, dconf)))
    t.start()
    status = None
    try:
        deadline = time.time() + 60
        driver = None
        while time.time() < deadline:
            driver = experiment.CURRENT_DRIVER
            if driver is not None and driver.server is not None and driver.server.port:
                break
            time.sleep(0.05)
        assert driver is not None
        client = rpc.Client(
            ("127.0.0.1", driver.server.port), partition_id=-1,
            secret=driver.server.secret,
        )
        try:
            while time.time() < deadline:
                s = client._request({"type": "STATUS"})
                gauges = (s.get("telemetry") or {}).get("0", {}).get("gauges") or {}
                # early beats carry only connection gauges; wait for fit's
                if "step_time_ms" in gauges and "steps_per_sec" in gauges:
                    status = s
                    break
                time.sleep(0.05)
        finally:
            client.stop()
    finally:
        release.set()
        t.join(timeout=120)

    # live STATUS carried the heartbeat-attached snapshot...
    assert status is not None, "no STATUS with telemetry arrived"
    gauges = status["telemetry"]["0"]["gauges"]
    assert gauges["step_time_ms"] > 0 and gauges["steps_per_sec"] > 0
    # ...which the monitor renders as the throughput/step-time panel
    panel = render_status(status)
    assert "-- telemetry --" in panel
    assert "ms/step" in panel and "tok/s" in panel

    # returned metrics expose steps/sec (averaged into the dist result)
    assert holder["r"]["steps_per_sec"] > 0

    # durable artifacts: per-worker JSONL + structurally valid merged trace
    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    tdir = os.path.join(exp_dir, "telemetry")
    worker_file = os.path.join(tdir, "worker_0.jsonl")
    assert os.path.exists(worker_file)
    records = [json.loads(l) for l in open(worker_file) if l.strip()]
    assert any(r.get("name") == "train_step" for r in records)
    trace_path = os.path.join(tdir, "trace.json")
    assert os.path.exists(trace_path)
    trace = json.load(open(trace_path))
    events = trace["traceEvents"]
    assert events
    for e in events:
        assert {"ph", "ts", "pid", "tid", "name"} <= set(e)
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    assert any(e["ph"] == "X" and e["name"] == "train_step" for e in events)


# ------------------------------------------------------- monitor satellites


def test_resolve_target_skips_and_prunes_stale_records(tmp_env, capsys):
    from maggy_tpu.monitor import resolve_target

    # live driver: a real listening socket
    live = socket.socket()
    live.bind(("127.0.0.1", 0))
    live.listen(1)
    live_port = live.getsockname()[1]
    # a port that refuses connections
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()

    try:
        tmp_env.register_driver("app_live", 1, "127.0.0.1", live_port,
                                secret="s1", scope="local")
        time.sleep(0.01)  # registry orders by ts: make the dead record newest
        tmp_env.register_driver("app_dead", 1, "127.0.0.1", dead_port,
                                secret="s2", scope="local")
        host, port, secret = resolve_target(tmp_env)
        assert (host, port, secret) == ("127.0.0.1", live_port, "s1")
        # the stale record was pruned from the registry
        assert tmp_env.lookup_driver("app_dead") is None
        assert tmp_env.lookup_driver("app_live") is not None

        # nothing live left -> LookupError naming the pruned count
        tmp_env.unregister_driver("app_live")
        tmp_env.register_driver("app_dead2", 1, "127.0.0.1", dead_port,
                                secret="s3", scope="local")
        with pytest.raises(LookupError, match="stale"):
            resolve_target(tmp_env)
    finally:
        live.close()


# ----------------------------------------------------------------- CI lint


def test_no_bare_print_lint():
    """tools/check_no_bare_print.py runs clean over maggy_tpu/ (wired into
    tier-1 here so regressions fail the suite)."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_no_bare_print", os.path.join(repo, "tools", "check_no_bare_print.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0

    # the detector itself: bare print flagged, file=-routed print allowed
    assert mod.find_bare_prints("print('x')", "<s>") != []
    assert mod.find_bare_prints("import sys\nprint('x', file=sys.stderr)", "<s>") == []
    assert mod.find_bare_prints("obj.print('x')", "<s>") == []


def test_docs_nav_lint(tmp_path):
    """tools/check_docs_nav.py: every docs/*.md is reachable from the mkdocs
    nav (wired into tier-1 here, alongside the bare-print lint)."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_docs_nav", os.path.join(repo, "tools", "check_docs_nav.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([repo]) == 0

    # the detector itself: an orphaned page is flagged, a referenced one not
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "linked.md").write_text("# linked")
    (tmp_path / "docs" / "orphan.md").write_text("# orphan")
    (tmp_path / "mkdocs.yml").write_text(
        "site_name: x\nnav:\n  - Linked: linked.md\ntheme:\n  name: mkdocs\n"
    )
    assert mod.orphaned_docs(str(tmp_path)) == [os.path.join("docs", "orphan.md")]
    assert mod.main([str(tmp_path)]) == 1
