"""End-to-end lagom() runs over the full stack: front door -> driver -> RPC
server -> executor threads -> train_fn -> result aggregation. The analogue of
the reference's only e2e test (test_randomsearch.py:67-101) with broader
coverage: multiple executors, ASHA budgets, early stopping, errored train_fns,
and single-run experiments."""

import os
import time

import pytest

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import BaseConfig, HyperparameterOptConfig


def space():
    return Searchspace(x=("DOUBLE", [0.0, 1.0]), y=("DOUBLE", [0.0, 1.0]))


def test_lagom_randomsearch_e2e(tmp_env):
    """5-step train_fn broadcasting metrics; result must identify the best trial."""

    def train(hparams, reporter):
        base = hparams["x"] * (1 - hparams["y"])
        for step in range(5):
            reporter.broadcast(base + step * 0.01, step=step)
        return base + 0.04

    cfg = HyperparameterOptConfig(
        num_trials=8,
        optimizer="randomsearch",
        searchspace=space(),
        direction="max",
        num_executors=4,
        es_policy="none",
        hb_interval=0.05,
        seed=5,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 8
    assert result["best"][cfg.optimization_key] >= result["worst"][cfg.optimization_key]
    p = result["best"]["params"]
    assert result["best"][cfg.optimization_key] == pytest.approx(
        p["x"] * (1 - p["y"]) + 0.04
    )
    # experiment artifacts persisted
    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    assert os.path.exists(os.path.join(exp_dir, "result.json"))
    trial_dirs = [d for d in os.listdir(exp_dir) if len(d) == 16]
    assert len(trial_dirs) == 8
    assert os.path.exists(os.path.join(exp_dir, trial_dirs[0], "trial.json"))


def test_lagom_asha_e2e(tmp_env):
    """ASHA: budget must reach the train_fn; more trials run than num_trials
    (promotions)."""
    budgets_seen = []

    def train(hparams, budget, reporter):
        budgets_seen.append(budget)
        for step in range(int(budget)):
            reporter.broadcast(hparams["x"], step=step)
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=8,
        optimizer="asha",
        searchspace=space(),
        direction="max",
        num_executors=4,
        es_policy="none",
        hb_interval=0.05,
        seed=0,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] > 8  # base rung + promotions
    assert set(budgets_seen) == {1, 2, 4}


def test_lagom_early_stopping(tmp_env):
    """Bad trials must be stopped mid-flight by the median rule."""

    def train(hparams, reporter):
        quality = hparams["x"]
        for step in range(200):
            reporter.broadcast(quality, step=step)
            time.sleep(0.002)
        return quality

    cfg = HyperparameterOptConfig(
        num_trials=6,
        optimizer="randomsearch",
        searchspace=space(),
        direction="max",
        num_executors=2,
        es_policy="median",
        es_interval=0,  # check on every heartbeat digest
        es_min=2,
        hb_interval=0.02,
        seed=11,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 6
    assert result["early_stopped"] > 0


def test_lagom_failing_train_fn_aborts(tmp_env):
    def train(hparams):
        raise RuntimeError("broken train fn")

    cfg = HyperparameterOptConfig(
        num_trials=4,
        optimizer="randomsearch",
        searchspace=space(),
        num_executors=2,
        es_policy="none",
        hb_interval=0.05,
    )
    with pytest.raises(RuntimeError, match="broken train fn"):
        experiment.lagom(train, cfg)


def test_lagom_partial_failures_tolerated(tmp_env):
    """Once successes exist, sporadic trial errors must not kill the experiment."""
    calls = {"n": 0}

    def train(hparams, reporter):
        calls["n"] += 1
        if calls["n"] == 3:
            raise ValueError("flaky trial")
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=6,
        optimizer="randomsearch",
        searchspace=space(),
        num_executors=1,  # deterministic ordering: first trials succeed
        es_policy="none",
        hb_interval=0.05,
        seed=2,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 6
    assert result["errors"] == 1


def test_lagom_base_config_single_run(tmp_env):
    def train(hparams, reporter):
        reporter.broadcast(1.0, step=0)
        return {"metric": 0.5, "note": 7}

    result = experiment.lagom(train, BaseConfig(hparams={}, hb_interval=0.05))
    assert result["metric"] == 0.5
    assert result["note"] == 7


def test_lagom_single_experiment_guard(tmp_env):
    import threading

    release = threading.Event()

    def slow_train(hparams):
        release.wait(5)
        return 1.0

    cfg = HyperparameterOptConfig(
        num_trials=1,
        optimizer="randomsearch",
        searchspace=space(),
        num_executors=1,
        es_policy="none",
        hb_interval=0.05,
    )
    t = threading.Thread(target=lambda: experiment.lagom(slow_train, cfg))
    t.start()
    time.sleep(0.3)
    try:
        with pytest.raises(RuntimeError, match="already running"):
            experiment.lagom(lambda hparams: 1.0, cfg)
    finally:
        release.set()
        t.join(timeout=10)


def test_lagom_train_fn_prints_ship_to_logs(tmp_env):
    """A train_fn's plain print() must land in the executor's log plane
    (reference hijacks builtins.print, trial_executor.py:93-103) — here via
    the thread-local tee, so concurrent executor threads don't cross wires."""

    def train(hparams, reporter):
        print(f"printed-marker x={hparams['x']:.4f}")
        reporter.broadcast(hparams["x"], step=0)
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=4,
        optimizer="randomsearch",
        searchspace=space(),
        direction="max",
        num_executors=2,
        es_policy="none",
        hb_interval=0.05,
        seed=0,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 4
    root = tmp_env.root
    app = next(a for a in os.listdir(root) if a.startswith("application_"))
    run = sorted(os.listdir(os.path.join(root, app)))[0]
    exp = os.path.join(root, app, run)
    per_file = {}
    for name in os.listdir(exp):
        if name.startswith("executor_") and name.endswith(".log"):
            with open(os.path.join(exp, name)) as f:
                per_file[name] = f.read()
    assert sum(t.count("printed-marker") for t in per_file.values()) == 4
    # per-thread isolation: each executor's prints must sit in ITS OWN log
    # next to that executor's trial lifecycle lines, not pooled in one file
    busy = [t for t in per_file.values() if "printed-marker" in t]
    assert len(busy) == 2, f"prints pooled into {len(busy)} file(s)"


def test_lagom_injects_train_context(tmp_env):
    """A train_fn asking for ``ctx`` gets a lease-wide TrainContext (built
    lazily — metric-only train_fns never touch jax)."""
    seen = {}

    def train(hparams, ctx):
        seen["ctx"] = ctx
        return 1.0

    cfg = HyperparameterOptConfig(
        num_trials=1,
        optimizer="randomsearch",
        searchspace=space(),
        num_executors=1,
        es_policy="none",
        hb_interval=0.05,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 1
    from maggy_tpu.train.trainer import TrainContext

    assert isinstance(seen["ctx"], TrainContext)


@pytest.mark.slow
def test_async_beats_bsp_wallclock(tmp_env):
    """The reference's ONE published benchmark (DistributedML'20): async
    trial assignment completes a fixed random-search budget in 33-58% less
    wall-clock than synchronous BSP waves. Reproduced through the REAL
    control plane (driver + RPC + executor threads) against the BSP cost of
    the SAME per-trial durations. Conservative bounds: heavy-tailed trials
    (the paper's regime) must clear 25%; even uniform durations must show
    a double-digit win."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from bench_async_vs_bsp import bsp_wall, run_async

    wall_u, durs_u = run_async(48, 8, "uniform", seed=1)
    red_u = 1.0 - wall_u / bsp_wall(durs_u, 8)
    wall_h, durs_h = run_async(48, 8, "heavy_tail", seed=1)
    red_h = 1.0 - wall_h / bsp_wall(durs_h, 8)
    assert red_h > 0.25, (red_h, wall_h)
    assert red_u > 0.10, (red_u, wall_u)
