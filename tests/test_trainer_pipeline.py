"""Trainer-integrated pipeline parallelism (VERDICT r3 item 2): a mesh with
stage>1 must actually train the real Decoder under 1F1B — same numbers as the
dense path — or raise loudly, never silently replicate the stage axis."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import TrainContext
from maggy_tpu.train.trainer import lm_loss_fn

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh"
)

# pp x tp / pp x ep compose via nested PARTIAL-manual shard_maps (the inner
# one inherits the context mesh with stage/data/fsdp already Manual); that
# abstract-mesh machinery only exists on newer jax — full-manual pp x dp/fsdp
# works everywhere
needs_partial_manual = pytest.mark.skipif(
    not hasattr(jax.sharding, "get_abstract_mesh"),
    reason="pp x tp/ep needs newer jax (abstract-mesh partial-manual shard_map)",
)


def _batch(cfg, bsz=8, seq=32, seed=0):
    rng = np.random.default_rng(seed)
    return {"tokens": rng.integers(0, cfg.vocab_size, (bsz, seq)).astype(np.int32)}


def test_pp_trainer_matches_dense_loss_and_grads():
    """pp=2 1F1B step == dense jax.grad on the same params (loss and grads,
    compared through unstack)."""
    cfg = DecoderConfig.tiny()
    batch = _batch(cfg)

    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-2))
    trainer.n_microbatches = 2
    state = trainer.make_state(jax.random.key(0), batch)

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))

    model = Decoder(cfg)

    def dense_loss(params):
        return lm_loss_fn(model.apply({"params": params}, batch["tokens"]), batch)

    ref_loss, ref_grads = jax.value_and_grad(dense_loss)(dense_params)

    new_state, metrics = trainer.step(state, trainer.shard_batch(batch))
    assert abs(float(metrics["loss"]) - float(ref_loss)) < 2e-3

    # grads: recover from the sgd update (p_new = p - lr * g)
    got = jax.jit(parts.unstack)(new_state.params)
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
    flat_old = dict(jax.tree_util.tree_leaves_with_path(dense_params))
    flat_new = dict(jax.tree_util.tree_leaves_with_path(jax.device_get(got)))
    for path, g_ref in flat_ref:
        g_got = (flat_old[path] - flat_new[path]) / 1e-2
        np.testing.assert_allclose(
            np.asarray(g_got), np.asarray(g_ref), atol=5e-2,
            err_msg=jax.tree_util.keystr(path),
        )


def test_pp_trainer_loss_decreases_and_eval_matches():
    cfg = DecoderConfig.tiny()
    batch = _batch(cfg)
    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-2))
    trainer.n_microbatches = 2
    state = trainer.make_state(jax.random.key(0), batch)
    losses = []
    for _ in range(5):
        state, m = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]

    # eval path under pp: unstacked apply equals the stage-stacked state
    parts = trainer._pipeline_parts()
    dense_params = jax.jit(parts.unstack)(state.params)
    ref = Decoder(cfg).apply({"params": dense_params}, jnp.asarray(batch["tokens"]))
    got = trainer.eval_logits(state, trainer.shard_batch(batch))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(jax.device_get(ref)), atol=1e-4
    )


def test_pp_four_stages():
    """4 stages x 2 dp on the deeper tiny config; restack round-trips."""
    cfg = DecoderConfig.tiny(n_layers=4)
    batch = _batch(cfg, bsz=8)
    ctx = TrainContext.create(ShardingSpec(pp=4, dp=2))
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-2))
    trainer.n_microbatches = 4
    state = trainer.make_state(jax.random.key(1), batch)
    state, m = trainer.step(state, trainer.shard_batch(batch))
    assert np.isfinite(float(m["loss"]))

    parts = trainer._pipeline_parts()
    stacked = jax.jit(parts.restack)(jax.jit(parts.unstack)(state.params))
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(jax.device_get(stacked)),
        jax.tree_util.tree_leaves_with_path(jax.device_get(state.params)),
    ):
        assert pa == pb
        if "embedding" in jax.tree_util.keystr(pa) or "final_norm" in jax.tree_util.keystr(pa) or "lm_head" in jax.tree_util.keystr(pa):
            continue  # broadcast leaves only round-trip their owning stage
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)


def test_pp_loss_mask_matches_dense_weighting():
    """Uneven loss_mask density across microbatches: the pp step must report
    the dense path's global mask-weighted mean, not an average of
    per-microbatch masked means (which would up-weight sparse microbatches)."""
    cfg = DecoderConfig.tiny()
    batch = _batch(cfg)
    mask = np.zeros_like(batch["tokens"])
    mask[:2] = 1          # dense rows in microbatch 0
    mask[2:, :3] = 1      # sparse rows elsewhere
    batch["loss_mask"] = mask

    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-2), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)
    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    ref = lm_loss_fn(
        Decoder(cfg).apply({"params": dense_params}, jnp.asarray(batch["tokens"])),
        {k: jnp.asarray(v) for k, v in batch.items()},
    )
    _, metrics = trainer.step(state, trainer.shard_batch(batch))
    assert abs(float(metrics["loss"]) - float(ref)) < 2e-3


def test_pp_packed_sequences_match_dense():
    """Packed batch (segment_ids + per-segment positions) under pp=2: the
    1F1B loss must equal dense jax.grad's on the same params — side inputs
    reach every stage through the raw channel stream. Segmentation is
    UNEVEN across microbatches (rows 0-3: four segments; rows 4-7: one) so
    a per-microbatch masked-mean average — different denominators — would
    diverge from the dense global masked mean."""
    cfg = DecoderConfig.tiny()
    B, S = 8, 32
    rng = np.random.default_rng(0)
    seg = np.zeros((B, S), np.int32)
    for i, b in enumerate((8, 16, 24)):  # rows 0-3: 4 segments
        seg[:4, b:] = i + 1
    pos4 = np.concatenate([np.arange(8)] * 4)
    pos1 = np.arange(S)
    pos = np.stack([pos4] * 4 + [pos1] * 4).astype(np.int32)
    batch = {
        "tokens": rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32),
        "positions": pos,
        "segment_ids": seg,
    }

    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-1), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)
    # train a few packed steps first: at init every loss is ~ln(V), so a
    # broken segment path would be indistinguishable — trained params are
    # segment-sensitive
    for _ in range(5):
        state, _ = trainer.step(state, trainer.shard_batch(batch))

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    jb = {k: jnp.asarray(v) for k, v in batch.items()}
    ref_packed = lm_loss_fn(
        Decoder(cfg).apply(
            {"params": dense_params}, jb["tokens"], jb["positions"], jb["segment_ids"]
        ),
        jb,
    )
    ref_plain = lm_loss_fn(
        Decoder(cfg).apply({"params": dense_params}, jb["tokens"]),
        {"tokens": jb["tokens"]},
    )
    # the mask demonstrably matters at these params...
    assert abs(float(ref_packed) - float(ref_plain)) > 1e-3
    # ...and the pp step's loss matches the dense PACKED reference
    _, metrics = trainer.step(state, trainer.shard_batch(batch))
    assert abs(float(metrics["loss"]) - float(ref_packed)) < 2e-3


def test_pp_moe_decoder_trains_with_router_aux():
    """MoEDecoder under pp=2: per-stage router aux losses join the
    objective at each stage's backward tick — total loss matches the dense
    trainer's (loss + aux) on the same params, and training decreases it."""
    from maggy_tpu.models import MoEConfig, MoEDecoder

    cfg = MoEConfig.tiny_moe()
    batch = _batch(cfg, bsz=8, seq=16)

    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    trainer = ctx.trainer(MoEDecoder(cfg), optax.sgd(1e-2), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)
    parts = trainer._pipeline_parts()
    assert parts.stage_has_aux
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))

    # dense reference: loss + summed router aux (the Trainer's dense path)
    from maggy_tpu.train.trainer import collect_aux_losses

    model = MoEDecoder(cfg)
    logits, mods = model.apply(
        {"params": dense_params}, jnp.asarray(batch["tokens"]),
        mutable=["intermediates"],
    )
    ref_loss = float(lm_loss_fn(logits, batch))
    ref_aux = float(collect_aux_losses(mods))
    assert ref_aux > 0

    state, metrics = trainer.step(state, trainer.shard_batch(batch))
    # pp reports the SAME metric semantics as the dense path. aux matches
    # approximately: balancing statistics are means over each microbatch's
    # routing groups, the dense pass computes them over the full batch
    assert abs(float(metrics["loss"]) - ref_loss) < 2e-3
    assert abs(float(metrics["aux_loss"]) - ref_aux) < 1e-3
    assert float(metrics["aux_loss"]) > 0
    assert abs(
        float(metrics["total_loss"]) - (ref_loss + ref_aux)
    ) < 3e-3
    losses = [float(metrics["total_loss"])]
    for _ in range(4):
        state, m = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(m["total_loss"]))
    assert losses[-1] < losses[0]


def test_convert_pipeline_state_across_pp_degrees():
    """A pp=2 TrainState (params + adam mu/nu) re-staged to pp=4 must train
    identically: step the converted state and compare the loss with a fresh
    pp=4 state built from the same canonical params (checkpoint portability,
    SURVEY §5.4)."""
    from maggy_tpu.train.pipeline_adapter import convert_pipeline_state

    cfg = DecoderConfig.tiny(n_layers=4)
    batch = _batch(cfg, bsz=8)

    ctx2 = TrainContext.create(ShardingSpec(pp=2, dp=4))
    tr2 = ctx2.trainer(Decoder(cfg), optax.adamw(1e-2), n_microbatches=2)
    state2 = tr2.make_state(jax.random.key(5), batch)
    state2, m2 = tr2.step(state2, tr2.shard_batch(batch))  # warm adam state

    ctx4 = TrainContext.create(ShardingSpec(pp=4, dp=2))
    tr4 = ctx4.trainer(Decoder(cfg), optax.adamw(1e-2), n_microbatches=4)
    parts2, parts4 = tr2._pipeline_parts(), tr4._pipeline_parts()
    converted = convert_pipeline_state(jax.device_get(state2), parts2, parts4)
    # params round-trip exactly through the re-staging
    np.testing.assert_allclose(
        np.asarray(parts4.unstack(converted.params)["embedding"]),
        np.asarray(jax.device_get(jax.jit(parts2.unstack)(state2.params))["embedding"]),
        atol=0,
    )
    # adopt_state computes shardings from shapes alone (no throwaway init),
    # rebinds the static fields, and places every leaf
    state4 = tr4.adopt_state(converted, batch)
    state4, m4 = tr4.step(state4, tr4.shard_batch(batch))
    # same params + same batch -> same loss on the next step, any pp degree
    state2b, m2b = tr2.step(state2, tr2.shard_batch(batch))
    assert abs(float(m4["loss"]) - float(m2b["loss"])) < 2e-3


def test_pp_raises_loudly_for_unsupported():
    import flax.linen as nn

    class NotADecoder(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    cfg = DecoderConfig.tiny()
    batch = _batch(cfg)

    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    trainer = ctx.trainer(NotADecoder(), optax.sgd(1e-2))
    with pytest.raises(ValueError, match="Decoder"):
        trainer.make_state(jax.random.key(0), {"inputs": np.zeros((8, 4), np.float32)})

    # pp x sp: a seq-ring collective inside the 1F1B schedule's per-stage
    # lax.cond deadlocks (non-uniform predicate) — refuse loudly
    # (pp x tp and pp x ep ARE supported — see test_pp_tp_* / test_pp_ep_*)
    ctx2 = TrainContext.create(ShardingSpec(pp=2, dp=2, sp=2))
    tr2 = ctx2.trainer(Decoder(cfg), optax.sgd(1e-2))
    with pytest.raises(ValueError, match="does not compose with sp"):
        tr2.make_state(jax.random.key(0), batch)

    # layer count must split evenly into stages
    ctx3 = TrainContext.create(ShardingSpec(pp=4, dp=2))
    tr3 = ctx3.trainer(Decoder(DecoderConfig.tiny(n_layers=2)), optax.sgd(1e-2))
    with pytest.raises(ValueError, match="divisible"):
        tr3.make_state(jax.random.key(0), batch)

    # tied embeddings would silently untie across stages
    ctx4 = TrainContext.create(ShardingSpec(pp=2, dp=4))
    tr4 = ctx4.trainer(
        Decoder(DecoderConfig.tiny(tie_embeddings=True)), optax.sgd(1e-2)
    )
    with pytest.raises(ValueError, match="tie_embeddings"):
        tr4.make_state(jax.random.key(0), batch)

    # microbatch rows must shard over data x fsdp: clear error, not shard_map's
    ctx5 = TrainContext.create(ShardingSpec(pp=2, dp=4))
    tr5 = ctx5.trainer(Decoder(cfg), optax.sgd(1e-2), n_microbatches=4)
    state5 = tr5.make_state(jax.random.key(0), batch)  # bsz=8 -> mb=2 < dpf=4
    with pytest.raises(ValueError, match="microbatches"):
        tr5.step(state5, tr5.shard_batch(batch))


@needs_partial_manual
def test_pp_tp_matches_dense_loss_and_grads():
    """pp=2 x tp=2 x dp=2 (VERDICT r4 item 2): stage params carry
    tensor-sharded dims (attn heads / mlp hidden / vocab — the model's own
    logical axes resolved through the Trainer rules), the pipeline shard_map
    stays manual over stage/data/fsdp with `tensor` in GSPMD-auto mode, and
    the 1F1B step matches dense jax.grad on the same params."""
    cfg = DecoderConfig.tiny()
    batch = _batch(cfg)

    ctx = TrainContext.create(ShardingSpec(pp=2, tp=2, dp=2))
    trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-2), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)

    # placement: heads/mlp/vocab dims really sit on the tensor axis
    specs = {
        jax.tree_util.keystr(p): leaf.sharding.spec
        for p, leaf in jax.tree_util.tree_leaves_with_path(state.params)
    }
    assert specs["['embedding']"] == jax.sharding.PartitionSpec(
        "stage", "tensor", None
    )
    assert "tensor" in specs["['layers']['layer']['attn']['wq']['kernel']"]
    assert "tensor" in specs["['layers']['layer']['mlp']['w_gate']['kernel']"]
    assert "tensor" in specs["['lm_head']['kernel']"]

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    model = Decoder(cfg)

    def dense_loss(params):
        return lm_loss_fn(model.apply({"params": params}, batch["tokens"]), batch)

    ref_loss, ref_grads = jax.value_and_grad(dense_loss)(dense_params)

    new_state, metrics = trainer.step(state, trainer.shard_batch(batch))
    assert abs(float(metrics["loss"]) - float(ref_loss)) < 2e-3

    got = jax.device_get(jax.jit(parts.unstack)(new_state.params))
    flat_ref = jax.tree_util.tree_leaves_with_path(ref_grads)
    flat_old = dict(jax.tree_util.tree_leaves_with_path(dense_params))
    flat_new = dict(jax.tree_util.tree_leaves_with_path(got))
    for path, g_ref in flat_ref:
        g_got = (flat_old[path] - flat_new[path]) / 1e-2
        np.testing.assert_allclose(
            np.asarray(g_got), np.asarray(g_ref), atol=5e-2,
            err_msg=jax.tree_util.keystr(path),
        )


@needs_partial_manual
def test_pp_tp_trains_and_eval_matches():
    """pp x tp under adamw decreases the loss; eval_logits through the
    unstacked model matches a host-side dense apply (bf16 reduction-order
    tolerance: tensor-partitioned einsums reduce in a different order)."""
    cfg = DecoderConfig.tiny()
    batch = _batch(cfg)
    ctx = TrainContext.create(ShardingSpec(pp=2, tp=2, dp=2))
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-2), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)
    losses = []
    for _ in range(4):
        state, m = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    ref = Decoder(cfg).apply({"params": dense_params}, jnp.asarray(batch["tokens"]))
    got = trainer.eval_logits(state, trainer.shard_batch(batch))
    np.testing.assert_allclose(
        np.asarray(jax.device_get(got)), np.asarray(jax.device_get(ref)), atol=3e-2
    )


@needs_partial_manual
def test_pp_tp_moe_trains():
    """MoEDecoder under pp x tp: expert FFN hidden dims tensor-shard inside
    each stage; router aux still joins per stage."""
    from maggy_tpu.models import MoEConfig, MoEDecoder

    cfg = MoEConfig.tiny_moe()
    batch = _batch(cfg, bsz=8, seq=16)
    ctx = TrainContext.create(ShardingSpec(pp=2, tp=2, dp=2))
    trainer = ctx.trainer(MoEDecoder(cfg), optax.adamw(1e-2), n_microbatches=2)
    state = trainer.make_state(jax.random.key(1), batch)
    losses = []
    for _ in range(3):
        state, m = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(m["total_loss"]))
    assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0]
    assert float(m["aux_loss"]) > 0


def test_pp_pipelined_eval_loss_bounded_memory():
    """VERDICT r4 item 9: evaluate() under pp computes the loss THROUGH the
    pipeline stages (forward-only sweep) — matching the dense loss, with
    compiled temp memory well under the unstack-everything eval it
    replaced (at scale the dominant win is never materializing the full
    replicated param set)."""
    cfg = DecoderConfig.tiny()
    batch = _batch(cfg)
    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-2), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)
    for _ in range(3):
        state, _ = trainer.step(state, trainer.shard_batch(batch))

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    jb = {k: jnp.asarray(v) for k, v in batch.items()}
    ref = float(
        lm_loss_fn(Decoder(cfg).apply({"params": dense_params}, jb["tokens"]), jb)
    )
    res = trainer.evaluate(state, iter([batch] * 2), 2)
    assert abs(res["loss"] - ref) < 2e-3

    # live-bytes bound: the pipelined eval's compiled temp allocation must be
    # well under the replicated-unstack eval it replaced
    def replicated_eval(state, b):
        params = parts.unstack(state.params)
        return lm_loss_fn(Decoder(cfg).apply({"params": params}, b["tokens"]), b)

    sb = trainer.shard_batch(batch)
    with trainer.mesh:
        pip = trainer._eval_loss_step.lower(state, sb).compile()
        rep = jax.jit(replicated_eval).lower(state, sb).compile()
    pip_temp = pip.memory_analysis().temp_size_in_bytes
    rep_temp = rep.memory_analysis().temp_size_in_bytes
    assert pip_temp < rep_temp * 0.6, (pip_temp, rep_temp)


def test_pp_pipelined_eval_packed_matches_dense():
    """Packed batches evaluate through the pipeline too: side inputs ride
    the raw channel stream, and the masked global-mean rescale keeps the
    reported loss equal to the dense packed loss."""
    cfg = DecoderConfig.tiny()
    B, S = 8, 32
    rng = np.random.default_rng(1)
    seg = np.zeros((B, S), np.int32)
    seg[:4, S // 2:] = 1  # rows 0-3 packed, rows 4-7 single-doc
    pos = np.stack(
        [np.concatenate([np.arange(S // 2), np.arange(S - S // 2)])] * 4
        + [np.arange(S)] * 4
    ).astype(np.int32)
    batch = {
        "tokens": rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32),
        "positions": pos,
        "segment_ids": seg,
    }
    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-1), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)
    for _ in range(4):
        state, _ = trainer.step(state, trainer.shard_batch(batch))

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    jb = {k: jnp.asarray(v) for k, v in batch.items()}
    ref = float(lm_loss_fn(
        Decoder(cfg).apply(
            {"params": dense_params}, jb["tokens"], jb["positions"], jb["segment_ids"]
        ),
        jb,
    ))
    res = trainer.evaluate(state, iter([batch] * 2), 2)
    assert abs(res["loss"] - ref) < 2e-3


@needs_partial_manual
def test_pp_tp_packed_matches_dense():
    """Packed batch under pp x tp: segment ids reach the nested
    tensor-manual stage attention (replicated across head shards) and the
    loss matches dense packed ground truth on trained params."""
    cfg = DecoderConfig.tiny()
    B, S = 8, 32
    rng = np.random.default_rng(0)
    seg = np.zeros((B, S), np.int32)
    seg[:, S // 2:] = 1
    pos = np.concatenate(
        [np.arange(S // 2), np.arange(S - S // 2)]
    )[None].repeat(B, 0).astype(np.int32)
    batch = {
        "tokens": rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32),
        "positions": pos,
        "segment_ids": seg,
    }
    ctx = TrainContext.create(ShardingSpec(pp=2, tp=2, dp=2))
    trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-1), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)
    for _ in range(4):
        state, _ = trainer.step(state, trainer.shard_batch(batch))

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    jb = {k: jnp.asarray(v) for k, v in batch.items()}
    ref = lm_loss_fn(
        Decoder(cfg).apply(
            {"params": dense_params}, jb["tokens"], jb["positions"], jb["segment_ids"]
        ),
        jb,
    )
    _, metrics = trainer.step(state, trainer.shard_batch(batch))
    assert abs(float(metrics["loss"]) - float(ref)) < 2e-3


@needs_partial_manual
def test_pp_ep_moe_matches_dense():
    """pp x ep: expert FFN weights shard over the expert axis INSIDE each
    stage (GSPMD-auto in the pipeline's partial-manual region), and the
    step matches the dense trainer's loss + router aux on the same params."""
    from maggy_tpu.models import MoEConfig, MoEDecoder
    from maggy_tpu.train.trainer import collect_aux_losses

    cfg = MoEConfig.tiny_moe()
    batch = _batch(cfg, bsz=8, seq=16)
    ctx = TrainContext.create(ShardingSpec(pp=2, ep=2, dp=2))
    trainer = ctx.trainer(MoEDecoder(cfg), optax.sgd(1e-2), n_microbatches=2)
    state = trainer.make_state(jax.random.key(0), batch)

    # placement: expert dims really sit on the expert axis
    specs = {
        jax.tree_util.keystr(p): leaf.sharding.spec
        for p, leaf in jax.tree_util.tree_leaves_with_path(state.params)
    }
    assert any("expert" in str(s) for s in specs.values()), specs

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    model = MoEDecoder(cfg)
    logits, mods = model.apply(
        {"params": dense_params}, jnp.asarray(batch["tokens"]),
        mutable=["intermediates"],
    )
    ref_loss = float(lm_loss_fn(logits, batch))
    ref_aux = float(collect_aux_losses(mods))

    state, metrics = trainer.step(state, trainer.shard_batch(batch))
    assert abs(float(metrics["loss"]) - ref_loss) < 2e-3
    assert abs(float(metrics["aux_loss"]) - ref_aux) < 1e-3
    assert float(metrics["aux_loss"]) > 0


def test_pp_ep_dense_model_refused():
    """ep>1 under pp with a NON-MoE model has no expert dims to shard — the
    axis would silently replicate every stage param; refuse loudly."""
    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create(ShardingSpec(pp=2, ep=2, dp=2))
    trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-2), n_microbatches=2)
    with pytest.raises(ValueError, match="needs an MoE model"):
        trainer.make_state(jax.random.key(0), _batch(cfg))


@needs_partial_manual
def test_pp_tp_ep_three_way_composition():
    """pp x tp x ep on one mesh: attention heads tensor-sharded AND expert
    FFNs expert-sharded inside each pipeline stage, training end-to-end."""
    from maggy_tpu.models import MoEConfig, MoEDecoder

    cfg = MoEConfig.tiny_moe()
    batch = _batch(cfg, bsz=8, seq=16)
    ctx = TrainContext.create(ShardingSpec(pp=2, tp=2, ep=2))
    trainer = ctx.trainer(MoEDecoder(cfg), optax.adamw(1e-2), n_microbatches=2)
    state = trainer.make_state(jax.random.key(1), batch)

    specs = [
        str(leaf.sharding.spec)
        for _, leaf in jax.tree_util.tree_leaves_with_path(state.params)
    ]
    assert any("expert" in s for s in specs)
    assert any("tensor" in s for s in specs)

    # dense-reference parity, same bar as the 2-way composition tests: a
    # subtly wrong 3-way layout that still "trains" must not pass
    from maggy_tpu.train.trainer import collect_aux_losses

    parts = trainer._pipeline_parts()
    dense_params = jax.device_get(jax.jit(parts.unstack)(state.params))
    logits, mods = MoEDecoder(cfg).apply(
        {"params": dense_params}, jnp.asarray(batch["tokens"]),
        mutable=["intermediates"],
    )
    ref_loss = float(lm_loss_fn(logits, batch))
    ref_aux = float(collect_aux_losses(mods))

    losses = []
    for i in range(3):
        state, m = trainer.step(state, trainer.shard_batch(batch))
        if i == 0:
            assert abs(float(m["loss"]) - ref_loss) < 2e-3
            assert abs(float(m["aux_loss"]) - ref_aux) < 1e-3
        losses.append(float(m["total_loss"]))
    assert losses[-1] < losses[0]
    assert float(m["aux_loss"]) > 0


@needs_partial_manual
def test_restore_pp_checkpoint_onto_pp_tp_mesh():
    """Checkpoint portability across LAYOUTS, not just degrees: a state
    trained on a plain pp=2 x dp mesh adopts onto a pp=2 x tp=2 mesh —
    adopt_state recomputes the tensor-sharded placements from shapes alone
    — and the next step's loss matches continuing on the original mesh."""
    cfg = DecoderConfig.tiny()
    batch = _batch(cfg)

    ctx_pp = TrainContext.create(ShardingSpec(pp=2, dp=4))
    tr_pp = ctx_pp.trainer(Decoder(cfg), optax.adamw(1e-2), n_microbatches=2)
    state = tr_pp.make_state(jax.random.key(3), batch)
    state, _ = tr_pp.step(state, tr_pp.shard_batch(batch))  # warm adam

    ctx_tp = TrainContext.create(ShardingSpec(pp=2, tp=2, dp=2))
    tr_tp = ctx_tp.trainer(Decoder(cfg), optax.adamw(1e-2), n_microbatches=2)
    adopted = tr_tp.adopt_state(jax.device_get(state), batch)

    # placements really are the pp x tp layout now
    specs = {
        jax.tree_util.keystr(p): leaf.sharding.spec
        for p, leaf in jax.tree_util.tree_leaves_with_path(adopted.params)
    }
    assert "tensor" in str(specs["['layers']['layer']['attn']['wq']['kernel']"])

    _, m_tp = tr_tp.step(adopted, tr_tp.shard_batch(batch))
    _, m_pp = tr_pp.step(state, tr_pp.shard_batch(batch))
    assert abs(float(m_tp["loss"]) - float(m_pp["loss"])) < 2e-3
