"""Ablation subsystem tests: study spec, LOCO trial generation, and a full
lagom e2e ablation over a flax model factory + dict dataset (the BERT-base
ablation BASELINE config in miniature)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu import experiment
from maggy_tpu.ablation import AblationStudy
from maggy_tpu.ablation.ablationstudy import default_dataset_generator
from maggy_tpu.ablation.ablator import LOCO
from maggy_tpu.config import AblationConfig


def study():
    s = AblationStudy()
    s.features.include("f1", "f2")
    s.model.layers.include("block_a", "block_b", "head_extra")
    s.model.layers.include_groups(["block_a", "block_b"])
    s.model.layers.include_groups(prefix="block_")
    return s


def test_study_spec():
    s = study()
    assert s.features.list_all() == ["f1", "f2"]
    assert s.model.layers.included == ["block_a", "block_b", "head_extra"]
    groups = s.model.layers.included_groups
    assert frozenset(["block_a", "block_b"]) in groups
    assert len(groups) == 1  # prefix group resolves to the same set -> deduped
    d = s.to_dict()
    assert d["components"] == ["block_a", "block_b", "head_extra"]


def test_prefix_group_requires_matches():
    s = AblationStudy()
    s.model.layers.include_groups(prefix="nope_")
    with pytest.raises(ValueError, match="matches no included components"):
        s.model.layers.included_groups


def test_loco_trial_enumeration():
    s = study()
    s.model.add_custom_generator("wide", lambda: "wide-model")
    loco = LOCO(s)
    loco.initialize()
    assert loco.get_number_of_trials() == 1 + 2 + 3 + 1 + 1
    trials = []
    while True:
        t = loco.get_trial()
        if t is None:
            break
        trials.append(t)
    assert len(trials) == 8
    # baseline first
    assert trials[0].params == {"ablated_feature": "None", "ablated_component": "None"}
    feats = [t.params["ablated_feature"] for t in trials]
    comps = [t.params["ablated_component"] for t in trials]
    assert "f1" in feats and "f2" in feats
    assert "block_a" in comps and "block_a|block_b" in comps
    assert "custom:wide" in comps
    # ids unique
    assert len({t.trial_id for t in trials}) == 8


def test_default_dataset_generator():
    ds = {"f1": np.zeros(4), "f2": np.ones(4), "label": np.ones(4)}
    out = default_dataset_generator(ds, "f1")
    assert set(out) == {"f2", "label"}
    assert default_dataset_generator(ds, None) is ds
    with pytest.raises(KeyError):
        default_dataset_generator(ds, "missing")
    with pytest.raises(TypeError):
        default_dataset_generator([1, 2], "f1")


def test_lagom_ablation_e2e(tmp_env):
    """Feature + component LOCO over a real (tiny) flax model; the component
    that matters must show the largest metric drop."""
    import flax.linen as nn

    rng = np.random.default_rng(0)
    n = 256
    # f1 is predictive, f2 is noise
    f1 = rng.normal(size=(n, 4)).astype(np.float32)
    f2 = rng.normal(size=(n, 4)).astype(np.float32)
    y = (f1.sum(-1) > 0).astype(np.int32)
    dataset = {"f1": f1, "f2": f2, "label": y}

    class Net(nn.Module):
        ablated: frozenset = frozenset()

        @nn.compact
        def __call__(self, x):
            h = nn.Dense(16, name="enc")(x)
            if "deep" not in self.ablated:
                h = nn.relu(nn.Dense(16, name="deep")(h))
            return nn.Dense(2, name="out")(h)

    s = AblationStudy()
    s.features.include("f2")
    s.model.layers.include("deep")
    s.model.set_factory(lambda ablated: Net(ablated=ablated))

    def train(model, dataset, reporter):
        feats = np.concatenate(
            [dataset[k] for k in sorted(dataset) if k != "label"], axis=-1
        )
        labels = dataset["label"]
        params = model.init(jax.random.key(0), feats)

        @jax.jit
        def step(p, x, yb):
            def loss_fn(p):
                logits = model.apply(p, x)
                logp = jax.nn.log_softmax(logits)
                return -jnp.take_along_axis(logp, yb[:, None], axis=-1).mean()

            l, g = jax.value_and_grad(loss_fn)(p)
            return jax.tree.map(lambda a, b: a - 0.5 * b, p, g), l

        for i in range(40):
            params, l = step(params, feats, labels)
        acc = float((jnp.argmax(model.apply(params, feats), -1) == labels).mean())
        reporter.broadcast(acc, step=0)
        return acc

    cfg = AblationConfig(
        ablation_study=s,
        direction="max",
        num_executors=3,
        hb_interval=0.05,
    )
    cfg.dataset = dataset
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 3  # baseline + f2 + deep
    assert result["best"]["metric"] > 0.9
    # all three variants produced valid metrics
    assert result["errors"] == 0
