"""Autopilot acceptance (ISSUE 8): diagnosis taxonomy + evidence, the
planner's registry-bounded moves, workload-fingerprint decision sharing,
the tune-cache alias scoping fix, the knob-registry lint (wired into
tier-1 here), live knob application (prefetcher depth, engine slot
reconfigure), the workload-shift re-tune + forced-regression rollback
state machine, fit integration, and the end-to-end serve demo: a workload
shift triggers an online re-tune whose measured after-window beats the
before-window, an injected regression rolls back automatically, and both
decisions are visible as ``autopilot.*`` telemetry and on the monitor
panel."""

import importlib.util
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import pytest

from maggy_tpu import telemetry
from maggy_tpu.autopilot import (
    AutopilotConfig,
    Controller,
    DecisionStore,
    Move,
    Planner,
    diagnose_requests,
    diagnose_serve,
    diagnose_steps,
    diagnose_train,
    traffic_shape,
    workload_fingerprint,
)
from maggy_tpu.autopilot.knobs import KNOBS
from maggy_tpu.telemetry import attribution
from maggy_tpu.telemetry.recorder import Telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def autopilot_events(tel):
    return [
        e
        for e in tel.drain_events()
        if str(e.get("name", "")).startswith("autopilot.")
        and e.get("kind") == "event"
    ]


# ---------------------------------------------------------------- diagnoser


def test_diagnose_train_taxonomy_with_evidence():
    d = diagnose_train(
        {"step_time_ms": 100.0, "input_wait_ms": 40.0, "metrics_drain_ms": 2.0}
    )
    assert d.bottleneck == "input_bound" and d.scope == "train"
    # the evidence struct names the metrics behind the verdict
    assert d.evidence["input_wait_ms"] == 40.0
    assert d.shares["input"] == pytest.approx(0.4)
    assert "input_wait_ms" in d.reason

    d = diagnose_train(
        {"step_time_ms": 100.0, "input_wait_ms": 2.0, "metrics_drain_ms": 30.0}
    )
    assert d.bottleneck == "drain_bound"

    d = diagnose_train(
        {"step_time_ms": 100.0, "input_wait_ms": 2.0, "metrics_drain_ms": 1.0}
    )
    assert d.bottleneck == "compute_bound"

    d = diagnose_train(
        {"step_time_ms": 100.0, "input_wait_ms": 90.0, "memory_headroom_frac": 0.01}
    )
    assert d.bottleneck == "memory_bound"  # memory outranks everything
    assert json.loads(json.dumps(d.to_dict()))["bottleneck"] == "memory_bound"


def test_diagnose_serve_taxonomy():
    flood = {
        "queue_depth": 10, "active_slots": 2, "num_slots": 2,
        "tpot_ms_p50": 5.0, "drain_ms": 0.2,
    }
    assert diagnose_serve(flood).bottleneck == "queue_bound"
    drainy = {
        "queue_depth": 0, "active_slots": 2, "num_slots": 4,
        "tpot_ms_p50": 5.0, "drain_ms": 3.0,
    }
    assert diagnose_serve(drainy).bottleneck == "drain_bound"
    assert (
        diagnose_serve(
            {"queue_depth": 0, "active_slots": 0, "num_slots": 4}
        ).bottleneck
        == "idle"
    )
    healthy = {
        "queue_depth": 1, "active_slots": 2, "num_slots": 4,
        "tpot_ms_p50": 5.0, "drain_ms": 0.1,
    }
    assert diagnose_serve(healthy).bottleneck == "compute_bound"


def test_diagnoser_and_cli_share_the_attribution_code_path(tmp_path):
    """Satellite: ``analyze_trace --json`` and the Diagnoser consume the
    SAME module — the tool's analyze() IS attribution.analyze, the JSON is
    schema-stamped, and diagnose_steps reads its step_summary verbatim."""
    tool = load_tool("analyze_trace")
    assert tool.analyze is attribution.analyze

    tdir = os.path.join(str(tmp_path), "telemetry")
    os.makedirs(tdir)
    with open(os.path.join(tdir, "worker_0.jsonl"), "w") as f:
        for step, wait in ((20.0, 9.0), (22.0, 11.0)):
            f.write(json.dumps({"kind": "gauge", "name": "step_time_ms",
                                "ts": 1.0, "value": step, "worker": "0"}) + "\n")
            f.write(json.dumps({"kind": "gauge", "name": "input_wait_ms",
                                "ts": 1.0, "value": wait, "worker": "0"}) + "\n")
    result = attribution.analyze(str(tmp_path))
    assert result["schema"] == attribution.SCHEMA
    # machine-readable output round-trips and diagnoses input-bound
    back = json.loads(json.dumps(result, sort_keys=True, default=str))
    d = diagnose_steps(back["step_summary"])
    assert d.bottleneck == "input_bound"
    assert d.evidence["step_time_ms"] == pytest.approx(21.0)

    # request-side: queue-dominated attribution diagnoses queue_bound
    d = diagnose_requests(
        {
            "requests": 4,
            "components_ms_mean": {"queue": 80.0, "decode": 20.0},
            "components_share": {"queue": 0.8, "decode": 0.2},
        }
    )
    assert d.bottleneck == "queue_bound"

    # the CLI prints the same object under --json
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert tool.main([str(tmp_path), "--json"]) == 0
    printed = json.loads(buf.getvalue())
    assert printed["schema"] == attribution.SCHEMA
    assert printed["step_summary"] == back["step_summary"]


# ------------------------------------------------------------------ planner


def test_planner_respects_registry_bounds_and_liveness():
    p = Planner()
    d = diagnose_train(
        {"step_time_ms": 100.0, "input_wait_ms": 40.0, "metrics_drain_ms": 0.0}
    )
    (move,) = p.plan(d, {"train.prefetch_depth": 2, "train.metrics_window": 2})
    assert move.knob == "train.prefetch_depth" and move.value == 4

    # at the registry ceiling the doubling clamps; the no-op is dropped
    hi = int(KNOBS["train.prefetch_depth"].hi)
    assert p.plan(d, {"train.prefetch_depth": hi}) == []

    # memory_bound plans only startup knobs -> nothing survives live_only
    dm = diagnose_train(
        {"step_time_ms": 100.0, "input_wait_ms": 0.0, "memory_headroom_frac": 0.0}
    )
    current = {"train.batch_size": 32, "train.remat_policy": None}
    assert p.plan(dm, current, live_only=True) == []
    offline = {m.knob: m.value for m in p.plan(dm, current, live_only=False)}
    assert offline["train.batch_size"] == 16
    assert offline["train.remat_policy"] == "nothing"

    # feasibility hook prunes exactly like the startup tuner would
    p2 = Planner(feasible=lambda m: m.knob != "train.batch_size")
    offline2 = {m.knob for m in p2.plan(dm, current, live_only=False)}
    assert "train.batch_size" not in offline2 and "train.remat_policy" in offline2

    # a move can never target an unregistered knob
    with pytest.raises(ValueError):
        Move("train.nonexistent_knob", 1)


def test_planner_serve_queue_bound_escalates_to_admission():
    p = Planner()
    d = diagnose_serve(
        {"queue_depth": 99, "active_slots": 2, "num_slots": 2, "tpot_ms_p50": 5.0}
    )
    (move,) = p.plan(d, {"serve.num_slots": 2})
    assert move.knob == "serve.num_slots" and move.value == 4
    # slot geometry already at its registry ceiling: shed instead
    hi = int(KNOBS["serve.num_slots"].hi)
    (move,) = p.plan(
        d, {"serve.num_slots": hi, "fleet.admission": "queue"}
    )
    assert move.knob == "fleet.admission" and move.value == "shed"


# ----------------------------------------------------------------- CI lint


def test_check_knob_registry_lint():
    """tools/check_knob_registry.py runs clean over maggy_tpu/ (wired into
    tier-1 here); its detector catches unregistered Move targets, KNOBS
    subscripts, and knob-shaped literals in the autopilot package; and the
    registry self-check catches structurally bad entries."""
    mod = load_tool("check_knob_registry")
    assert mod.main([]) == 0

    registry = mod.load_registry(REPO)
    flag = lambda src, ap=False: mod.check_source(  # noqa: E731
        src, "<s>", registry, in_autopilot_pkg=ap
    )
    assert flag("Move('serve.num_slots', 4)") == []
    assert flag("Move('serve.num_slotz', 4)") != []
    assert flag("plan.Move(knob='train.prefetch_depht', value=2)") != []
    assert flag("KNOBS['fleet.admission']") == []
    assert flag("KNOBS['fleet.admision']") != []
    # knob-shaped literals are references inside the autopilot package only
    assert flag("x = 'serve.not_a_knob'", ap=True) != []
    assert flag("x = 'serve.not_a_knob'", ap=False) == []
    assert flag("tel.gauge('autopilot.tick_ms', 1)", ap=True) == []

    # registry structural self-check
    bad = dict(registry.KNOBS)
    bad["train.broken"] = registry.Knob(
        "train.broken", "int", "train", True, "missing bounds"
    )
    errs = registry.validate_registry(bad)
    assert any("lo <= hi" in e for e in errs)
    assert registry.validate_registry() == []


# ------------------------------------------- workload fingerprint + sharing


def test_workload_fingerprint_and_traffic_buckets():
    topo = {"n_devices": 8, "platform": "cpu", "n_processes": 1}
    t1 = traffic_shape("serve", prompt_len=100, offered_rps=20)
    t2 = traffic_shape("serve", prompt_len=120, offered_rps=17)
    assert t1 == t2  # power-of-two buckets: near-identical traffic shares
    a = workload_fingerprint("model-a", topo, t1)
    assert a == workload_fingerprint("model-a", topo, t2)
    assert a != workload_fingerprint("model-b", topo, t1)
    assert a != workload_fingerprint("model-a", {**topo, "n_processes": 2}, t1)
    assert a != workload_fingerprint("model-a", topo, traffic_shape("train"))


class KnobTarget:
    """Synthetic push-mode target: knobs apply instantly, samples are
    whatever the test scripts."""

    def __init__(self, scope="train", guard="steps_per_sec", knobs=None):
        self.scope = scope
        self.guard_metric = guard
        self.knobs = dict(knobs or {})
        self.applied = []

    def sample(self):
        return {}

    def pending(self):
        return False

    def current(self):
        return dict(self.knobs)

    def apply(self, knob, value):
        self.applied.append((knob, value))
        self.knobs[knob] = value
        return True


def test_decision_store_fleet_sharing(tmp_env):
    """A committed decision under a workload fingerprint seeds the next
    controller for the same workload — the fleet-shared cache."""
    wfp = workload_fingerprint("m", {"n_devices": 8}, traffic_shape("train"))
    store = DecisionStore()
    store.record(
        wfp, Move("train.prefetch_depth", 8, "test"),
        outcome="committed", before=1.0, after=2.0,
    )
    assert store.load(wfp) == {"train.prefetch_depth": 8}
    # a different workload reads nothing (scoped, not last-writer-wins)
    assert store.load("someone-else") == {}

    tel = Telemetry(worker="seed-test")
    target = KnobTarget(knobs={"train.prefetch_depth": 2, "train.metrics_window": 2})
    Controller(target, AutopilotConfig(window=4), telemetry_recorder=tel, workload=wfp)
    assert target.knobs["train.prefetch_depth"] == 8
    evs = autopilot_events(tel)
    assert any(
        e["name"] == "autopilot.applied"
        and e["attrs"]["reason"] == "decision cache"
        for e in evs
    )


def test_tune_cache_alias_scoped_per_workload(tmp_env):
    """Satellite: the tune-cache 'latest' alias is scoped per workload
    fingerprint — distinct topologies get distinct alias keys (process
    layout included), and a record stamped for another workload reads as
    a miss, never as this job's winner."""
    from maggy_tpu.tune.cache import (
        TuneCache,
        alias_cache_key,
        alias_workload,
        topology_key,
    )

    topo_a = {"n_devices": 8, "platform": "cpu", "device_kind": "cpu", "n_processes": 1}
    topo_b = {**topo_a, "n_processes": 2}
    assert alias_cache_key("fp", topo_a, "bf16") != alias_cache_key("fp", topo_b, "bf16")
    assert "n_processes" in topology_key()  # live topologies carry the layout

    cache = TuneCache()
    key = alias_cache_key("fp", topo_a, "bf16")
    wl_a = alias_workload("fp", topo_a, "bf16")
    record = {"best": {"x": 1}, "workload": wl_a}
    cache.put(key, record)
    assert cache.get_alias(key, wl_a) == record
    # another workload's stamp at the same key is a MISS (anti-clobber)
    assert cache.get_alias(key, alias_workload("fp", topo_b, "bf16")) is None
    # a clobber by a different-workload writer poisons nobody
    cache.put(key, {"best": {"x": 2}, "workload": "other"})
    assert cache.get_alias(key, wl_a) is None


# -------------------------------------------------- controller state machine


def feed(controller, sample, n):
    for _ in range(n):
        controller.observe(dict(sample))


def test_workload_shift_retunes_and_journals(tmp_env):
    """Satellite scenario: an input-bound run flips to drain-bound
    mid-run; the controller re-diagnoses, applies the planned move each
    time, and every decision lands in telemetry."""
    tel = Telemetry(worker="shift-test")
    target = KnobTarget(knobs={"train.prefetch_depth": 1, "train.metrics_window": 1})
    c = Controller(
        target,
        AutopilotConfig(window=4, cooldown_windows=0, store=False),
        telemetry_recorder=tel,
    )
    # phase A: input-bound at 5 steps/sec
    input_bound = {
        "step_time_ms": 200.0, "input_wait_ms": 120.0,
        "metrics_drain_ms": 1.0, "steps_per_sec": 5.0,
    }
    feed(c, input_bound, 4)  # baseline window -> diagnose + apply
    assert target.knobs["train.prefetch_depth"] == 2
    # trial window: the move helped (input wait gone, faster)
    feed(
        c,
        {"step_time_ms": 90.0, "input_wait_ms": 5.0,
         "metrics_drain_ms": 1.0, "steps_per_sec": 11.0},
        4,
    )
    assert c.retunes == 1 and c.rollbacks == 0

    # phase B: the workload shifts — now drain-bound
    drain_bound = {
        "step_time_ms": 100.0, "input_wait_ms": 2.0,
        "metrics_drain_ms": 40.0, "steps_per_sec": 10.0,
    }
    feed(c, drain_bound, 4)  # re-diagnose -> metrics_window move
    assert target.knobs["train.metrics_window"] == 2
    feed(
        c,
        {"step_time_ms": 70.0, "input_wait_ms": 2.0,
         "metrics_drain_ms": 5.0, "steps_per_sec": 14.0},
        4,
    )
    assert c.retunes == 2

    evs = autopilot_events(tel)
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e["attrs"])
    diags = [a["bottleneck"] for a in by_name["autopilot.diagnosis"]]
    assert "input_bound" in diags and "drain_bound" in diags
    # evidence rides in the journal
    assert all("evidence" in a for a in by_name["autopilot.diagnosis"])
    applied = [(a["knob"], a["value"]) for a in by_name["autopilot.applied"]]
    assert ("train.prefetch_depth", 2) in applied
    assert ("train.metrics_window", 2) in applied
    commits = [(a["knob"], a["guard_before"], a["guard_after"])
               for a in by_name["autopilot.committed"]]
    assert len(commits) == 2
    assert all(after > before for _, before, after in commits)


def test_forced_regression_rolls_back(tmp_env):
    """Satellite scenario: a move whose after-window regresses the guard
    is rolled back automatically and journaled."""
    tel = Telemetry(worker="rb-test")
    target = KnobTarget(knobs={"train.prefetch_depth": 1, "train.metrics_window": 1})
    c = Controller(
        target,
        AutopilotConfig(window=4, cooldown_windows=0, store=False),
        telemetry_recorder=tel,
    )
    input_bound = {
        "step_time_ms": 200.0, "input_wait_ms": 120.0,
        "metrics_drain_ms": 1.0, "steps_per_sec": 5.0,
    }
    feed(c, input_bound, 4)
    assert target.knobs["train.prefetch_depth"] == 2
    # trial window REGRESSES (guard 5 -> 2): automatic rollback
    feed(c, {**input_bound, "steps_per_sec": 2.0}, 4)
    assert c.rollbacks == 1 and c.retunes == 0
    assert target.knobs["train.prefetch_depth"] == 1  # restored
    evs = autopilot_events(tel)
    rb = [e["attrs"] for e in evs if e["name"] == "autopilot.rollback"]
    assert rb and rb[0]["restored"] == 1 and rb[0]["guard_after"] < rb[0]["guard_before"]


def test_controller_observe_overhead_budget():
    """The per-step controller cost (window append + amortized
    diagnose/plan) stays far under 2% of any realistic step — the CI
    mirror of bench.py extra.autopilot's gate."""
    target = KnobTarget(knobs={"train.prefetch_depth": 2, "train.metrics_window": 2})
    c = Controller(
        target,
        AutopilotConfig(window=16, cooldown_windows=0, store=False),
        telemetry_recorder=Telemetry(worker="ovh"),
    )
    sample = {
        "step_time_ms": 5.0, "input_wait_ms": 0.1,
        "metrics_drain_ms": 0.05, "steps_per_sec": 200.0,
    }
    n = 4000
    t0 = time.perf_counter()
    for _ in range(n):
        c.observe(dict(sample))
    per_obs_us = (time.perf_counter() - t0) / n * 1e6
    # 2% of even a 5 ms step is 100 us
    assert per_obs_us < 100.0, per_obs_us


# ------------------------------------------------------- live knob plumbing


def test_prefetcher_set_depth_live():
    from maggy_tpu.train.prefetch import DevicePrefetcher

    src = iter(range(100))
    pf = DevicePrefetcher(src, put=lambda x: x, depth=1)
    try:
        assert next(pf) == 0
        time.sleep(0.1)  # producer tops up the depth-1 queue and blocks
        assert pf._queue.qsize() == 1
        pf.set_depth(4)
        deadline = time.time() + 2.0
        while pf._queue.qsize() < 4 and time.time() < deadline:
            time.sleep(0.01)
        assert pf._queue.qsize() == 4  # the larger lookahead filled live
        assert [next(pf) for _ in range(6)] == [1, 2, 3, 4, 5, 6]  # order kept
    finally:
        pf.close()


# --------------------------------------------------------- engine/scheduler

CFG = None


def _cfg():
    global CFG
    if CFG is None:
        from maggy_tpu.models import DecoderConfig

        CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    return CFG


@pytest.fixture(scope="module")
def params():
    from maggy_tpu.models import Decoder
    from maggy_tpu.parallel.sharding import unbox

    return unbox(
        Decoder(_cfg()).init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )


def _run_engine(engine, prompts, max_new=8):
    from maggy_tpu.serve import Request, SamplingParams
    from maggy_tpu.serve.slots import SlotOccupiedError

    out = {}
    todo = list(enumerate(prompts))
    streams = {}
    while todo or streams:
        while todo and engine.slots.free_slots():
            idx, p = todo.pop(0)
            try:
                slot, first = engine.admit(
                    Request(prompt=p, params=SamplingParams(max_new=max_new))
                )
            except SlotOccupiedError:
                todo.insert(0, (idx, p))
                break
            streams[slot] = (idx, [first])
        step = engine.step()
        done = []
        for slot, tok in step.tokens.items():
            idx, toks = streams[slot]
            toks.append(tok)
            if len(toks) >= max_new:
                done.append(slot)
        for slot in done:
            idx, toks = streams.pop(slot)
            out[idx] = toks
            engine.release(slot)
    engine.flush()
    return [out[i] for i in range(len(prompts))]


def test_engine_reconfigure_drain_and_byte_parity(params):
    """The drain-and-reconfigure seam: slot geometry changes between
    waves, refuses while occupied, and the reconfigured engine produces
    byte-identical streams to a fresh engine of the same geometry."""
    from maggy_tpu.serve import Engine, Request, SamplingParams
    from maggy_tpu.serve.slots import SlotOccupiedError

    prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10, 11]]
    eng = Engine(_cfg(), params, num_slots=2, telemetry_recorder=telemetry.NULL)
    first_wave = _run_engine(eng, prompts[:2])

    # refuses mid-wave
    slot, _ = eng.admit(Request(prompt=[9, 9], params=SamplingParams(max_new=4)))
    with pytest.raises(SlotOccupiedError):
        eng.reconfigure(4)
    eng.release(slot)

    eng.reconfigure(4)
    assert eng.slots.num_slots == 4
    after = _run_engine(eng, prompts)

    fresh = Engine(_cfg(), params, num_slots=4, telemetry_recorder=telemetry.NULL)
    expect = _run_engine(fresh, prompts)
    assert after == expect  # engine output = f(params, prompt, seed) only
    assert first_wave == expect[:2]


def test_serve_autopilot_e2e_demo(params, tmp_env):
    """End-to-end acceptance: a serve workload shift (trickle -> flood)
    makes the controller diagnose queue_bound, grow ``serve.num_slots``
    via drain-and-reconfigure, and COMMIT because the measured after-window
    beats the before-window; an injected regression (slots slashed to 1)
    then triggers automatic rollback to the prior geometry. Both decisions
    are `autopilot.*` telemetry events and visible on the monitor panel."""
    from maggy_tpu.monitor import render_status
    from maggy_tpu.serve import Engine, SamplingParams, Scheduler

    tel = Telemetry(worker="e2e")
    eng = Engine(_cfg(), params, num_slots=2, telemetry_recorder=tel)
    sched = Scheduler(
        eng,
        autopilot=AutopilotConfig(
            window=4, interval_s=0.05, cooldown_windows=0, store=False
        ),
    )
    sched.start()
    try:
        # phase 1 — trickle: a couple of requests, no queue pressure
        for _ in range(2):
            r = sched.submit([1, 2, 3], SamplingParams(max_new=4))
            deadline = time.time() + 60
            while r.state != "done" and time.time() < deadline:
                time.sleep(0.01)
        assert eng.slots.num_slots == 2

        # phase 2 — flood: sustained backlog until the re-tune commits
        committed_evs = []
        deadline = time.time() + 150
        i = 0
        while time.time() < deadline and sched.autopilot.retunes == 0:
            with sched._lock:
                depth = len(sched._queue)
            if depth < 24:
                sched.submit(
                    [1 + (i % 13), 2, 3 + (i % 5)], SamplingParams(max_new=24)
                )
                i += 1
            time.sleep(0.005)
        assert sched.autopilot.retunes >= 1, "flood never committed a re-tune"
        assert eng.slots.num_slots == 4  # the planned move, live

        evs = autopilot_events(tel)
        applied = [e["attrs"] for e in evs if e["name"] == "autopilot.applied"]
        committed_evs = [
            e["attrs"] for e in evs if e["name"] == "autopilot.committed"
        ]
        assert any(
            a["knob"] == "serve.num_slots" and a["value"] == 4 for a in applied
        )
        commit = next(
            a for a in committed_evs if a["knob"] == "serve.num_slots"
        )
        # the measured after-window beats the before-window
        assert commit["guard_after"] > commit["guard_before"]
        diags = [e["attrs"] for e in evs if e["name"] == "autopilot.diagnosis"]
        assert any(d["bottleneck"] == "queue_bound" for d in diags)

        # phase 3 — injected regression: slash the geometry, keep flooding
        assert sched.autopilot.inject(
            Move("serve.num_slots", 1, reason="chaos: forced regression")
        )
        deadline = time.time() + 150
        while time.time() < deadline and sched.autopilot.rollbacks == 0:
            with sched._lock:
                depth = len(sched._queue)
            if depth < 24:
                sched.submit(
                    [2 + (i % 11), 3, 4 + (i % 7)], SamplingParams(max_new=24)
                )
                i += 1
            time.sleep(0.005)
        assert sched.autopilot.rollbacks >= 1, "regression never rolled back"
        # wait out the rollback's own drain-and-reconfigure
        deadline = time.time() + 60
        while eng.slots.num_slots != 4 and time.time() < deadline:
            time.sleep(0.02)
        assert eng.slots.num_slots == 4  # restored to the prior config
        evs = autopilot_events(tel)
        rb = [e["attrs"] for e in evs if e["name"] == "autopilot.rollback"]
        assert any(
            a["knob"] == "serve.num_slots" and a["restored"] == 4 for a in rb
        )

        # monitor panel shows the decision trail
        status = {
            "name": "serve-demo", "kind": "serve", "state": "serving",
            "app_id": "serve-demo", "run_id": 0, "elapsed_s": 1.0,
            "serve": sched.stats(),
        }
        panel = render_status(status)
        assert "autopilot[" in panel
        assert "serve.num_slots" in panel
    finally:
        sched.stop()


def test_fit_autopilot_integration(tmp_env):
    """``Trainer.fit(autopilot=...)`` on an input-starved run: the
    controller diagnoses input_bound from the live gauges, applies the
    prefetch-depth move to the RUNNING loop, and journals the decision."""
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    cfg = DecoderConfig.tiny(n_layers=2, d_model=64, n_heads=2, d_ff=128)
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=0)
    state = trainer.make_state(jax.random.key(0), next(data))

    def starved(src):
        while True:
            time.sleep(0.03)  # loader far slower than the tiny step
            yield next(src)

    tel = Telemetry(worker="fit-ap")
    telemetry.set_current(tel)
    try:
        state, metrics = trainer.fit(
            state,
            starved(data),
            num_steps=14,
            prefetch=1,
            autopilot=AutopilotConfig(window=4, cooldown_windows=0),
        )
    finally:
        telemetry.set_current(None)
    assert metrics["steps_per_sec"] > 0
    evs = autopilot_events(tel)
    diags = [e["attrs"] for e in evs if e["name"] == "autopilot.diagnosis"]
    assert diags and any(d["bottleneck"] == "input_bound" for d in diags)
    applied = [e["attrs"] for e in evs if e["name"] == "autopilot.applied"]
    assert any(
        a["knob"] == "train.prefetch_depth" and a["value"] > 1 for a in applied
    )
    # the fit-side workload fingerprint names the decision-cache scope
    assert all(a.get("workload") for a in applied)


def test_monitor_renders_autopilot_counters():
    from maggy_tpu.monitor import _telemetry_lines

    status = {
        "telemetry": {
            "0": {
                "counters": {
                    "autopilot.diagnoses": 7,
                    "autopilot.retunes": 2,
                    "autopilot.rollbacks": 1,
                }
            }
        }
    }
    lines = "\n".join(_telemetry_lines(status, width=78))
    assert "autopilot diag=7 retune=2 rb=1" in lines
