"""Serving front-end over RPC: the full engine+scheduler+server+client path
on CPU. The smoke test IS the ISSUE 2 acceptance demo: >= 8 staggered
requests through B=4 slots with (a) greedy outputs equal to one-shot
``generate_cached``, (b) exactly one decode-step compile for the whole run
(asserted via the compile-count telemetry), and (c) TTFT / queue-depth /
tokens-per-sec gauges in the exported telemetry JSONL and the monitor
panel."""

import dataclasses
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.serve import Engine, Scheduler, ServeClient, ServeServer

CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = Decoder(CFG)
    return unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )


def reference(params, prompt, max_new):
    decode_model = Decoder(dataclasses.replace(CFG, decode=True))
    buf = np.zeros((1, len(prompt) + max_new), np.int32)
    buf[0, : len(prompt)] = prompt
    out = generate_cached(
        decode_model, params, jnp.asarray(buf), jnp.asarray([len(prompt)])
    )
    return list(np.asarray(out)[0, len(prompt):])


def serve_stack(params, tmp_path=None, num_slots=4):
    """(server, telemetry) — telemetry JSONL-backed when tmp_path given."""
    tel = None
    if tmp_path is not None:
        from maggy_tpu.telemetry import worker_telemetry

        tel = worker_telemetry("serve", str(tmp_path), role="serve")
    engine = Engine(CFG, params, num_slots=num_slots, telemetry_recorder=tel)
    server = ServeServer(Scheduler(engine))
    return server, tel


def test_acceptance_demo_staggered_requests(params, tmp_path, tmp_env):
    """8 requests, staggered arrivals, B=4 — the acceptance criteria."""
    server, tel = serve_stack(params, tmp_path, num_slots=4)
    host, port = server.start(host="127.0.0.1")
    prompts = [
        [1, 2, 3, 4],
        [5, 6, 7],
        [9, 10, 11, 12, 13],
        [2, 4, 6, 8, 10, 12],
        [7, 3],
        [20, 21, 22, 23],
        [30, 31],
        [40, 41, 42, 44, 45, 46, 47],
    ]
    max_new = 6
    results = {}
    errors = []

    def drive(i, prompt, delay):
        try:
            time.sleep(delay)
            with ServeClient((host, port), server.secret) as client:
                results[i] = client.generate(prompt, max_new=max_new, timeout=90)
        except Exception as e:  # noqa: BLE001 - surfaced via the errors list
            errors.append((i, repr(e)))

    try:
        threads = [
            threading.Thread(target=drive, args=(i, p, 0.03 * i))
            for i, p in enumerate(prompts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(results) == len(prompts)

        # (a) greedy equivalence with one-shot generate_cached, per request
        for i, prompt in enumerate(prompts):
            assert results[i] == reference(params, prompt, max_new), (
                f"request {i} (prompt {prompt}) diverges from one-shot decode"
            )

        # (b) the decode step compiled exactly once across the whole run
        with ServeClient((host, port), server.secret) as client:
            stats = client.stats()
            status = client._client._request({"type": "STATUS"})
        assert stats["compile_counts"]["decode"] == 1, stats["compile_counts"]
        assert stats["requests_done"] == len(prompts)
        assert stats["tokens_out"] >= len(prompts) * max_new
        assert stats["ttft_ms_p50"] is not None

        # (c1) monitor panel renders the serving status
        from maggy_tpu.monitor import render_status

        panel = render_status(status)
        assert "slots" in panel and "queue=" in panel
        assert "ttft p50" in panel and "decode compiles 1" in panel
    finally:
        server.stop()

    # (c2) gauges landed in the exported telemetry JSONL
    assert tel is not None
    tel.close()
    path = os.path.join(str(tmp_path), "telemetry", "worker_serve.jsonl")
    with open(path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    gauge_names = {r["name"] for r in records if r.get("kind") == "gauge"}
    for expected in (
        "serve.ttft_ms",
        "serve.queue_depth",
        "serve.tokens_per_sec",
        "serve.active_slots",
        "serve.decode_retraces",
    ):
        assert expected in gauge_names, (expected, sorted(gauge_names))
    # the recorded retrace gauge agrees with the compile-once assertion
    retraces = [
        r["value"] for r in records
        if r.get("kind") == "gauge" and r["name"] == "serve.decode_retraces"
    ]
    assert retraces and max(retraces) == 1.0


def test_cancel_and_deadline(params):
    server, _ = serve_stack(params)
    host, port = server.start(host="127.0.0.1")
    try:
        with ServeClient((host, port), server.secret) as client:
            # cancel mid-decode: a long request is stopped well short
            rid = client.submit([1, 2, 3], max_new=50)
            time.sleep(0.2)
            assert client.cancel(rid)
            snap = client.result(rid, timeout=30)
            assert snap["state"] == "cancelled"
            assert snap["n_tokens"] < 50
            # cancel of a finished request reports False
            done = client.submit([4, 5], max_new=2)
            client.result(done, timeout=30)
            assert client.cancel(done) is False
            # a deadline in the past expires without decoding
            rid = client.submit([6, 7, 8], max_new=20, deadline_s=-0.1)
            snap = client.result(rid, timeout=30)
            assert snap["state"] == "expired"
            assert snap["error"]
    finally:
        server.stop()


def test_submit_validation_over_rpc(params):
    from maggy_tpu.exceptions import RpcError

    server, _ = serve_stack(params)
    host, port = server.start(host="127.0.0.1")
    try:
        with ServeClient((host, port), server.secret) as client:
            with pytest.raises(RpcError, match="max_seq_len"):
                client.submit(list(range(60)), max_new=20)
            with pytest.raises(RpcError, match="list of token ids"):
                client._client._request({"type": "SUBMIT", "prompt": "oops"})
            with pytest.raises(RpcError, match="unknown request"):
                client.poll("nonexistent")
            # the connection survives every rejected submit
            assert client.stats()["requests_submitted"] == 0
    finally:
        server.stop()


@pytest.mark.slow
def test_churn_soak(params):
    """Slot churn under sustained mixed load: staggered arrivals, varied
    lengths/sampling, cancellations sprinkled in — every request terminates,
    the decode step never recompiles, and greedy requests still match their
    one-shot reference afterwards."""
    server, _ = serve_stack(params, num_slots=3)
    host, port = server.start(host="127.0.0.1")
    rng = np.random.default_rng(0)
    try:
        with ServeClient((host, port), server.secret) as client:
            greedy_cases = {}
            ids = []
            for i in range(40):
                plen = int(rng.integers(2, 14))
                prompt = [int(t) for t in rng.integers(1, CFG.vocab_size, plen)]
                max_new = int(rng.integers(1, 10))
                greedy = i % 3 != 0
                rid = client.submit(
                    prompt,
                    max_new=max_new,
                    temperature=0.0 if greedy else 0.9,
                    seed=i,
                )
                if greedy:
                    greedy_cases[rid] = (prompt, max_new)
                ids.append(rid)
                if i % 7 == 0:
                    client.cancel(rid)
                time.sleep(float(rng.uniform(0.0, 0.02)))
            snaps = {rid: client.result(rid, timeout=180) for rid in ids}
            stats = client.stats()
        assert all(s["done"] for s in snaps.values())
        assert stats["compile_counts"]["decode"] == 1, stats["compile_counts"]
        assert stats["requests_failed"] == 0, stats
        for rid, (prompt, max_new) in greedy_cases.items():
            if snaps[rid]["state"] != "done":
                continue  # cancelled greedy request
            assert snaps[rid]["tokens"] == reference(params, prompt, max_new)
    finally:
        server.stop()
