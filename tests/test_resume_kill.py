"""Experiment resume after a hard driver kill (VERDICT r1 item 10).

A subprocess runs a seeded random-search HPO and SIGKILLs ITSELF (driver,
server, and executor threads all die — the ungraceful crash) once enough
trials have been persisted. A second subprocess resumes via ``resume_from``
and must finish the experiment WITHOUT re-running any persisted trial
(``core/driver/hpo.py`` preload + suggestion-skip path).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # subprocess/multi-process tier

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

RUN_SCRIPT = textwrap.dedent(
    """
    import json, os, signal, sys, threading
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")  # the env var alone can lose
    # to an accelerator plugin's auto-registration

    from maggy_tpu import Searchspace, experiment
    from maggy_tpu.config import HyperparameterOptConfig

    KILL_AFTER = int(os.environ.get("MT_KILL_AFTER", "0"))
    ran_file = os.environ["MT_RAN_FILE"]
    lock = threading.Lock()

    def train(hparams, reporter):
        with lock:
            with open(ran_file, "a") as f:
                f.write(json.dumps(hparams) + "\\n")
        reporter.broadcast(hparams["x"], step=0)
        return hparams["x"]

    def killer():
        # SIGKILL the whole process (driver + executors) the moment enough
        # trials have PERSISTED — trial.json is the resume source of truth
        import time
        exp_dir = os.environ["MT_EXP_DIR"]
        while True:
            n = 0
            if os.path.isdir(exp_dir):
                for name in os.listdir(exp_dir):
                    if os.path.exists(os.path.join(exp_dir, name, "trial.json")):
                        n += 1
            if n >= KILL_AFTER:
                os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(0.01)

    if KILL_AFTER:
        threading.Thread(target=killer, daemon=True).start()

    cfg = HyperparameterOptConfig(
        num_trials=16,
        optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0]), y=("DOUBLE", [0.0, 1.0])),
        direction="max",
        num_executors=2,
        es_policy="none",
        hb_interval=0.02,
        seed=21,
        resume_from=os.environ.get("MT_RESUME_FROM") or None,
    )
    result = experiment.lagom(train, cfg)
    print("DONE", result["num_trials"], flush=True)
    """
).format(repo=REPO)


def _persisted_params(exp_dir):
    out = []
    for name in os.listdir(exp_dir):
        path = os.path.join(exp_dir, name, "trial.json")
        if os.path.exists(path):
            try:
                with open(path) as f:
                    rec = json.load(f)
            except json.JSONDecodeError:
                # SIGKILL mid-write can truncate the newest record; the
                # production loader tolerates this too (load_finalized_trials)
                continue
            if rec.get("status") == "FINALIZED":
                out.append(tuple(sorted(rec["params"].items())))
    return out


def test_resume_after_sigkill(tmp_path):
    script = tmp_path / "hpo_script.py"
    script.write_text(RUN_SCRIPT)
    app_dir = tmp_path / "logs" / "application_resume_test_0001" / "1"

    env = dict(os.environ)
    env.update(
        {
            "MAGGY_TPU_LOG_ROOT": str(tmp_path / "logs"),
            "MAGGY_TPU_APP_ID": "application_resume_test_0001",
            "MAGGY_TPU_RUN_ID": "1",
            "MT_EXP_DIR": str(app_dir),
            "MT_RAN_FILE": str(tmp_path / "ran1.jsonl"),
            "MT_KILL_AFTER": "6",
            "JAX_PLATFORMS": "cpu",
        }
    )
    env.pop("XLA_FLAGS", None)
    first = subprocess.run(
        [sys.executable, str(script)],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert first.returncode == -9, (first.returncode, first.stderr[-1000:])
    persisted = _persisted_params(str(app_dir))
    # killer fired at 6 files on disk; the newest may be truncated mid-write
    assert len(persisted) >= 5
    assert len(persisted) < 16, "crash came too late to exercise resume"

    # resume into a fresh run dir, same seed -> same suggestion stream
    env2 = dict(env)
    env2.update(
        {
            "MAGGY_TPU_APP_ID": "application_resume_test_0002",
            "MT_RAN_FILE": str(tmp_path / "ran2.jsonl"),
            "MT_KILL_AFTER": "0",
            "MT_EXP_DIR": str(tmp_path / "unused"),
            "MT_RESUME_FROM": str(app_dir),
        }
    )
    second = subprocess.run(
        [sys.executable, str(script)],
        env=env2,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=240,
    )
    assert second.returncode == 0, second.stderr[-2000:]
    assert "DONE 16" in second.stdout, second.stdout[-500:]

    with open(tmp_path / "ran2.jsonl") as f:
        reran = [tuple(sorted(json.loads(l).items())) for l in f]
    # nothing that survived the crash ran again...
    overlap = set(persisted) & set(reran)
    assert not overlap, f"{len(overlap)} persisted trials re-ran"
    # ...and together they cover the full experiment
    assert len(set(persisted) | set(reran)) == 16
