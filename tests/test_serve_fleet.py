"""Serving fleet (maggy_tpu/serve/fleet): router + replicas on CPU.

The acceptance demo IS the ISSUE 6 criteria: >= 8 staggered requests
through a 2-replica fleet complete with tokens byte-identical to
single-engine serving, and chaos-killing one replica mid-run still
completes every request via requeue + quarantine. Admission control, the
``state="requeued"`` POLL contract, client BUSY/failover behavior, and
clean-drain shutdown are covered at unit level (no engines) so the heavy
device work stays in exactly two tests.
"""

import dataclasses
import threading
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.exceptions import ServerBusyError
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.resilience import chaos
from maggy_tpu.serve import ServeClient
from maggy_tpu.serve.fleet import (
    ReplicaSpec,
    Router,
    RouterConfig,
    launch_fleet,
    projected_ttft_ms,
)
from maggy_tpu.serve.fleet.router import PENDING, REQUEUED, RouteEntry

CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = Decoder(CFG)
    return unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )


def reference(params, prompt, max_new):
    decode_model = Decoder(dataclasses.replace(CFG, decode=True))
    buf = np.zeros((1, len(prompt) + max_new), np.int32)
    buf[0, : len(prompt)] = prompt
    out = generate_cached(
        decode_model, params, jnp.asarray(buf), jnp.asarray([len(prompt)])
    )
    return list(np.asarray(out)[0, len(prompt):])


def fake_replica(index, num_slots=4):
    """A healthy-looking replica for router unit tests (no engine/port)."""
    return types.SimpleNamespace(
        index=index,
        state="up",
        spec=types.SimpleNamespace(num_slots=num_slots),
        describe=lambda: {"replica": index, "state": "up", "addr": None,
                          "restarts": 0, "devices": [], "uptime_s": 0.0},
        client=None,
    )


# --------------------------------------------------------------- acceptance


def test_fleet_acceptance_demo(params):
    """8 staggered requests through 2 replicas == single-engine tokens."""
    router = launch_fleet(ReplicaSpec(CFG, params, num_slots=2), replicas=2)
    host, port = router.start(host="127.0.0.1")
    prompts = [
        [1, 2, 3, 4],
        [5, 6, 7],
        [9, 10, 11, 12, 13],
        [2, 4, 6, 8, 10, 12],
        [7, 3],
        [20, 21, 22, 23],
        [30, 31],
        [40, 41, 42, 44, 45],
    ]
    max_new = 5
    results, errors = {}, []

    def drive(i, prompt, delay):
        try:
            time.sleep(delay)
            with ServeClient((host, port), router.secret) as client:
                results[i] = client.generate(prompt, max_new=max_new, timeout=120)
        except Exception as e:  # noqa: BLE001 - surfaced via the errors list
            errors.append((i, repr(e)))

    try:
        threads = [
            threading.Thread(target=drive, args=(i, p, 0.04 * i))
            for i, p in enumerate(prompts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        assert not errors, errors
        assert len(results) == len(prompts)
        # byte-identical to the one-shot single-engine reference, regardless
        # of which replica served which request
        for i, prompt in enumerate(prompts):
            assert results[i] == reference(params, prompt, max_new), (
                f"request {i} diverges from single-engine decode"
            )
        with ServeClient((host, port), router.secret) as client:
            stats = client.stats()
            status = client._client.request({"type": "STATUS"})
        assert stats["fleet"] is True
        assert stats["routing"]["routed"] == len(prompts)
        assert stats["routing"]["completed"] == len(prompts)
        assert stats["routing"]["requeued"] == 0
        # the fleet actually spread load: both replicas served something
        done_by_replica = [r["requests_done"] for r in stats["replicas"]]
        assert len(done_by_replica) == 2
        assert all(n > 0 for n in done_by_replica), done_by_replica

        # monitor renders the fleet panel (replica table + routing counters)
        from maggy_tpu.monitor import render_status

        panel = render_status(status)
        assert "fleet:" in panel and "routed=8" in panel
        assert "r0 [" in panel and "r1 [" in panel
    finally:
        router.stop()


def test_fleet_chaos_failover(params):
    """Chaos-kill replica 1 mid-stream: every request still completes with
    correct tokens; the dead replica shows quarantined in router stats."""
    chaos.install(chaos.Chaos.parse("replica_kill:replica=1"))
    router = launch_fleet(
        ReplicaSpec(CFG, params, num_slots=2),
        replicas=2,
        config=RouterConfig(max_restarts=0, quarantine_threshold=2),
    )
    host, port = router.start(host="127.0.0.1")
    prompts = [
        [1, 2, 3, 4],
        [5, 6, 7],
        [9, 10, 11, 12],
        [2, 4, 6, 8],
        [7, 3],
        [20, 21, 22],
    ]
    max_new = 30  # long streams so the kill lands mid-decode
    results, errors, seen_states = {}, [], set()

    def drive(i, prompt, delay):
        try:
            time.sleep(delay)
            with ServeClient((host, port), router.secret) as client:
                rid = client.submit(prompt, max_new=max_new)
                deadline = time.time() + 240
                while True:
                    snap = client.poll(rid)
                    seen_states.add(snap["state"])
                    if snap.get("done"):
                        results[i] = snap["tokens"]
                        return
                    assert snap["id"] == rid  # the id survives requeue
                    if time.time() > deadline:
                        raise TimeoutError(f"stuck in {snap['state']}")
                    time.sleep(0.01)
        except Exception as e:  # noqa: BLE001 - surfaced via the errors list
            errors.append((i, repr(e)))

    try:
        threads = [
            threading.Thread(target=drive, args=(i, p, 0.04 * i))
            for i, p in enumerate(prompts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, errors
        assert chaos.get().fired, "chaos rule never fired"
        for i, prompt in enumerate(prompts):
            assert results[i] == reference(params, prompt, max_new), (
                f"request {i} diverges after failover"
            )
        with ServeClient((host, port), router.secret) as client:
            stats = client.stats()
        assert stats["routing"]["requeued"] >= 1, stats["routing"]
        states = {r["replica"]: r["state"] for r in stats["replicas"]}
        assert states[1] in ("quarantined", "dead"), states
        assert states[0] == "up"
    finally:
        router.stop()
        chaos.reset()


@pytest.mark.slow
def test_fleet_respawn_within_budget(params):
    """With restart budget, a chaos-killed replica comes back: fresh engine,
    fresh port, requests keep completing on the re-grown fleet."""
    chaos.install(chaos.Chaos.parse("replica_kill:replica=0"))
    router = launch_fleet(
        ReplicaSpec(CFG, params, num_slots=2),
        replicas=2,
        config=RouterConfig(max_restarts=1, quarantine_threshold=2),
    )
    host, port = router.start(host="127.0.0.1")
    try:
        with ServeClient((host, port), router.secret) as client:
            first_wave = [
                client.submit([1 + i, 2, 3], max_new=20) for i in range(4)
            ]
            snaps = [client.result(r, timeout=240) for r in first_wave]
            assert all(s["state"] == "done" for s in snaps)
            # wait for the respawn to land
            deadline = time.time() + 120
            while time.time() < deadline:
                stats = client.stats()
                if stats["routing"]["respawned"] >= 1:
                    break
                time.sleep(0.1)
            assert stats["routing"]["respawned"] == 1, stats["routing"]
            # the re-grown fleet serves new work on both replicas
            second_wave = [
                client.submit([40 + i, 2], max_new=4) for i in range(4)
            ]
            snaps = [client.result(r, timeout=240) for r in second_wave]
            assert all(s["state"] == "done" for s in snaps)
            states = {r["replica"]: r["state"] for r in client.stats()["replicas"]}
            assert states == {0: "up", 1: "up"}, states
    finally:
        router.stop()
        chaos.reset()


# ------------------------------------------------------------ router units


def test_poll_reports_requeued_not_lost():
    """The satellite contract: POLL on a requeued request keeps the id and
    reports state='requeued' instead of an unknown-request error."""
    router = Router([fake_replica(0)], config=RouterConfig())
    entry = RouteEntry(rid="abc123", payload={"prompt": [1, 2, 3]})
    entry.state = REQUEUED
    entry.resubmits = 1
    router._entries["abc123"] = entry
    snap = router._on_poll({"id": "abc123"})
    assert snap["state"] == "requeued"
    assert snap["id"] == "abc123"
    assert snap["done"] is False
    assert snap["resubmits"] == 1
    # pending entries read as queued
    entry.state = PENDING
    assert router._on_poll({"id": "abc123"})["state"] == "queued"
    with pytest.raises(ValueError, match="unknown request"):
        router._on_poll({"id": "nope"})


def test_projected_ttft_model():
    # free slot + empty queue: one prefill at the observed p50
    assert projected_ttft_ms(
        {"num_slots": 4, "active_slots": 1, "queue_depth": 0, "ttft_ms_p50": 80},
        prior_ms=100.0,
    ) == 80.0
    # saturated: backlog waves stack on top
    loaded = projected_ttft_ms(
        {"num_slots": 4, "active_slots": 4, "queue_depth": 8, "ttft_ms_p50": 80},
        prior_ms=100.0,
    )
    assert loaded > 80.0 * 3  # (1 + 9/4) waves
    # no p50 yet: the prior stands in
    assert projected_ttft_ms({"num_slots": 2, "active_slots": 0,
                              "queue_depth": 0}, prior_ms=123.0) == 123.0


def test_admission_shed_vs_queue():
    """Projection over SLO sheds with a 429-style BUSY in shed mode and
    parks in the router queue in queue mode."""
    loaded = {"num_slots": 2, "active_slots": 2, "queue_depth": 10,
              "ttft_ms_p50": 100.0}
    shed_router = Router(
        [fake_replica(0, num_slots=2)],
        config=RouterConfig(slo_ttft_ms=150.0, admission="shed"),
    )
    shed_router._stats_cache[0] = dict(loaded)
    reply = shed_router._on_submit({"prompt": [1, 2, 3]})
    assert reply["type"] == "BUSY"
    assert reply["projected_ttft_ms"] > 150.0
    assert shed_router.counters["shed"] == 1

    queue_router = Router(
        [fake_replica(0, num_slots=2)],
        config=RouterConfig(slo_ttft_ms=150.0, admission="queue"),
    )
    queue_router._stats_cache[0] = dict(loaded)
    reply = queue_router._on_submit({"prompt": [1, 2, 3]})
    assert reply["type"] == "SUBMIT"
    snap = queue_router._on_poll({"id": reply["id"]})
    assert snap["state"] == "queued"
    # dispatch holds the parked request while projection stays over-SLO
    queue_router._dispatch_pending(time.time())
    assert queue_router._on_poll({"id": reply["id"]})["state"] == "queued"

    # no healthy replica: always a shed, both modes
    dead_router = Router([], config=RouterConfig())
    assert dead_router._on_submit({"prompt": [1]})["type"] == "BUSY"

    # malformed prompts rejected before admission
    with pytest.raises(ValueError, match="token ids"):
        shed_router._on_submit({"prompt": "oops"})


def test_requeue_outranks_fresh_and_skips_slo():
    """A requeued entry goes to the FRONT of the pending queue and is
    redispatched even when fresh admissions would be held by the SLO."""
    router = Router(
        [fake_replica(0, num_slots=2)],
        config=RouterConfig(slo_ttft_ms=1.0, admission="queue"),
    )
    router._stats_cache[0] = {"num_slots": 2, "active_slots": 2,
                              "queue_depth": 5, "ttft_ms_p50": 100.0}
    fresh = router._on_submit({"prompt": [1, 2]})["id"]
    requeued = RouteEntry(rid="rq1", payload={"prompt": [3, 4]})
    requeued.state = REQUEUED
    router._entries["rq1"] = requeued
    router._pending.appendleft("rq1")
    assert list(router._pending) == ["rq1", fresh]

    sent = []
    router.replicas[0].client = types.SimpleNamespace(
        submit=lambda **kw: sent.append(kw) or "remote-1"
    )
    router._dispatch_pending(time.time())
    # the requeued entry went out; the fresh one is still held by the SLO
    assert len(sent) == 1 and sent[0]["prompt"] == [3, 4]
    assert router._entries["rq1"].state == "routed"
    assert router._on_poll({"id": fresh})["state"] == "queued"


def test_client_busy_typed_and_retry_budget():
    """ServeClient surfaces BUSY as ServerBusyError (no blind retry) and
    honors an explicit retry_busy budget."""
    router = Router(
        [fake_replica(0, num_slots=2)],
        config=RouterConfig(slo_ttft_ms=10.0, admission="shed"),
    )
    router._stats_cache[0] = {"num_slots": 2, "active_slots": 2,
                              "queue_depth": 50, "ttft_ms_p50": 100.0}
    host, port = router._rpc.start(host="127.0.0.1")
    try:
        with ServeClient((host, port), router.secret) as client:
            with pytest.raises(ServerBusyError, match="BUSY|busy|SLO"):
                client.submit([1, 2, 3])
            before = router.counters["shed"]
            with pytest.raises(ServerBusyError):
                client.submit([1, 2, 3], retry_busy=2)
            # the budgeted retries actually re-asked the router
            assert router.counters["shed"] == before + 3
    finally:
        router._rpc.stop()


def test_clean_shutdown_sheds_new_submits():
    router = Router([fake_replica(0)], config=RouterConfig())
    router._closing = True
    assert router._on_submit({"prompt": [1, 2]})["type"] == "BUSY"


# -------------------------------------------------------- scheduler stats race


def test_scheduler_stats_race(params):
    """Concurrent SSTATS polling against a live scheduler loop never tears:
    the router hammers stats() from several threads while requests churn."""
    from maggy_tpu.serve import Engine, Scheduler, SamplingParams

    engine = Engine(CFG, params, num_slots=2)
    scheduler = Scheduler(engine)
    scheduler.start()
    errors = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                s = scheduler.stats()
                assert isinstance(s["queue_depth"], int)
                assert "prefix_hits" in s
            except Exception as e:  # noqa: BLE001 - the race under test
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        reqs = [
            scheduler.submit([1 + i, 2, 3], SamplingParams(max_new=4))
            for i in range(8)
        ]
        deadline = time.time() + 120
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in reqs
        ):
            time.sleep(0.01)
        assert all(r.state == "done" for r in reqs)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        scheduler.stop()
    assert not errors, errors
