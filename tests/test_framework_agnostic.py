"""The oblivious train_fn is framework-agnostic: a torch (CPU) training
function runs under lagom HPO unchanged — the migration path for reference
users whose train_fns are torch/keras code."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig


def test_torch_train_fn_under_lagom(tmp_env):
    rng = np.random.default_rng(0)
    X = torch.tensor(rng.normal(size=(256, 8)).astype(np.float32))
    w = torch.tensor(rng.normal(size=(8, 1)).astype(np.float32))
    y = (X @ w > 0).float()

    def train(hparams, reporter):
        torch.manual_seed(0)
        model = torch.nn.Sequential(
            torch.nn.Linear(8, hparams["width"]),
            torch.nn.ReLU(),
            torch.nn.Linear(hparams["width"], 1),
        )
        opt = torch.optim.Adam(model.parameters(), lr=hparams["lr"])
        loss_fn = torch.nn.BCEWithLogitsLoss()
        for step in range(60):
            opt.zero_grad()
            loss = loss_fn(model(X), y)
            loss.backward()
            opt.step()
            if step % 20 == 19:
                reporter.broadcast(-float(loss.item()), step=step)
        with torch.no_grad():
            acc = float(((model(X) > 0).float() == y).float().mean())
        return {"metric": acc}

    cfg = HyperparameterOptConfig(
        num_trials=4,
        optimizer="randomsearch",
        searchspace=Searchspace(
            lr=("DOUBLE", [1e-3, 1e-1]), width=("DISCRETE", [8, 16, 32])
        ),
        direction="max",
        num_executors=2,
        es_policy="none",
        hb_interval=0.05,
        seed=3,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 4
    assert result["best"]["metric"] > 0.9
    assert result["errors"] == 0
