"""Bucketed gradient overlap + ZeRO-1 optimizer-state sharding
(maggy_tpu/parallel/overlap.py and its Trainer/checkpoint integration).

Covers the tentpole contracts: bucket-plan geometry, flatten/unflatten and
optax-state conversions round-trip exactly, zero_stage=0/bucket_mb=inf is
bit-identical to the dense path, bucketed and ZeRO-1 steps track the dense
loss, ZeRO-1 shrinks optimizer bytes per device by ~1/data_width, checkpoint
round-trips across zero_stage and world-size transitions, and pp-composed
meshes fall back to the unbucketed path with a one-time warning.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel import overlap as ovl
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import pipeline_adapter
from maggy_tpu.train.checkpoint import Checkpointer, restore_zero_compat
from maggy_tpu.train.data import synthetic_lm_batches
from maggy_tpu.train.trainer import TrainContext


def _tree(seed=0):
    """Small mixed-shape/dtype param tree for plan/flatten unit tests."""
    rng = np.random.default_rng(seed)
    return {
        "emb": {"w": rng.normal(size=(7, 5)).astype(np.float32)},
        "layers": [
            {"k": rng.normal(size=(5, 5)).astype(np.float32),
             "b": rng.normal(size=(5,)).astype(np.float32)}
            for _ in range(3)
        ],
        "head": {"w": rng.normal(size=(5, 7)).astype(np.float32)},
    }


# ------------------------------------------------------------ plan geometry


def test_plan_buckets_reverse_order_and_cap():
    tree = _tree()
    leaves = jax.tree.leaves(tree)
    plan = ovl.plan_buckets(tree, bucket_mb=100 / 2**20)  # 100-byte cap
    assert plan.n_leaves == len(leaves)
    # bucket 0 holds the LAST flatten-order leaves (backward produces their
    # grads first), and indices across buckets walk strictly backwards
    flat_order = [i for b in plan.buckets for i in b.indices]
    assert flat_order[0] == len(leaves) - 1
    assert sorted(flat_order) == list(range(len(leaves)))
    for b in plan.buckets:
        assert list(b.indices) == sorted(b.indices, reverse=True)
        # the 100-byte cap is honored unless a single leaf exceeds it
        assert b.size * 4 <= 100 or len(b.indices) == 1
        assert b.size == sum(b.sizes)
    # names zero-padded so dict key-sort order == plan order
    names = [b.name for b in plan.buckets]
    assert names == sorted(names)


def test_plan_buckets_unbounded_padding_and_errors():
    tree = _tree()
    # None/inf cap -> one bucket for the whole (single-dtype) tree
    for cap in (None, float("inf")):
        plan = ovl.plan_buckets(tree, cap)
        assert len(plan.buckets) == 1
    # pad_to rounds every bucket to a shardable multiple
    plan = ovl.plan_buckets(tree, 100 / 2**20, pad_to=8)
    for b in plan.buckets:
        assert b.padded_size % 8 == 0 and b.padded_size >= b.size
    with pytest.raises(ValueError):
        ovl.plan_buckets({}, 1.0)
    with pytest.raises(ValueError):
        ovl.plan_buckets(tree, 1.0, pad_to=0)


def test_plan_buckets_splits_dtypes():
    tree = {
        "a": jnp.zeros((4,), jnp.float32),
        "b": jnp.zeros((4,), jnp.bfloat16),
        "c": jnp.zeros((4,), jnp.float32),
    }
    plan = ovl.plan_buckets(tree, None)
    # consecutive leaves of different dtype never share a flat vector
    assert len(plan.buckets) == 3
    assert [b.dtype for b in plan.buckets] == ["float32", "bfloat16", "float32"]


def test_flatten_unflatten_roundtrip():
    tree = _tree(1)
    plan = ovl.plan_buckets(tree, 120 / 2**20, pad_to=4)
    flats = ovl.flatten_buckets(tree, plan)
    assert set(flats) == {b.name for b in plan.buckets}
    for b in plan.buckets:
        assert flats[b.name].shape == (b.padded_size,)
    back = ovl.unflatten_buckets(flats, plan, tree)
    assert jax.tree.all(
        jax.tree.map(lambda a, b: bool(np.array_equal(a, b)), tree, back)
    )
    with pytest.raises(ValueError):
        ovl.flatten_buckets({"just": np.zeros(3)}, plan)


def test_opt_state_flatten_and_reflatten_roundtrip():
    tree = jax.tree.map(jnp.asarray, _tree(2))
    tx = optax.adamw(1e-3)
    opt = tx.update(jax.tree.map(jnp.ones_like, tree), tx.init(tree), tree)[1]
    plan = ovl.plan_buckets(tree, 100 / 2**20, pad_to=4)
    flat = ovl.flatten_opt_state(opt, plan, tree)
    # adam mu/nu became {bucket: vector} dicts; the count leaf passed through
    mu_flat = flat[0].mu
    assert set(mu_flat) == {b.name for b in plan.buckets}
    assert flat[0].count.shape == ()
    back = ovl.unflatten_opt_state(flat, plan, tree)
    assert jax.tree.all(
        jax.tree.map(lambda a, b: bool(np.array_equal(a, b)), opt, back)
    )
    # re-bucketing across plans (width/bucket_mb change) round-trips exactly
    plan2 = ovl.plan_buckets(tree, None, pad_to=2)
    re2 = ovl.reflatten_opt_state(flat, plan, plan2, tree)
    assert set(re2[0].mu) == {b.name for b in plan2.buckets}
    back2 = ovl.unflatten_opt_state(re2, plan2, tree)
    assert jax.tree.all(
        jax.tree.map(lambda a, b: bool(np.array_equal(a, b)), opt, back2)
    )


# --------------------------------------------------------- gauges / config


class _FakeTel:
    def __init__(self):
        self.gauges = {}

    def gauge(self, name, value):
        self.gauges[name] = value


def test_record_overlap_gauges():
    tel = _FakeTel()
    times = {
        "dense": 10.0, "bucketed": 7.0, "nocomm": 5.0,
        "only_data": 6.0, "only_slice": 8.5,
    }
    out = ovl.record_overlap_gauges(
        times, ("slice", "data"), telemetry_recorder=tel
    )
    assert out["comm_total_ms"] == pytest.approx(5.0)
    assert out["comm_exposed_ms"] == pytest.approx(2.0)
    assert out["comm_overlapped_ms"] == pytest.approx(3.0)
    assert tel.gauges["train.comm_exposed_ms"] == pytest.approx(2.0)
    assert tel.gauges["train.comm_overlapped_ms"] == pytest.approx(3.0)
    assert tel.gauges["train.comm_exposed_ms.data"] == pytest.approx(1.0)
    assert tel.gauges["train.comm_exposed_ms.slice"] == pytest.approx(3.5)


def test_sharding_spec_zero_fields():
    spec = ShardingSpec(dp=8, zero_stage=1, bucket_mb=4.0)
    assert spec.zero_stage == 1 and spec.bucket_mb == 4.0
    # scaled_to preserves the zero fields (dataclasses.replace path)
    scaled = spec.scaled_to(4)
    assert scaled.dp == 4 and scaled.zero_stage == 1 and scaled.bucket_mb == 4.0
    with pytest.raises(ValueError):
        ShardingSpec(dp=8, zero_stage=2)
    with pytest.raises(ValueError):
        ShardingSpec(dp=8, bucket_mb=0)


def test_distributed_config_zero_mapping():
    from maggy_tpu.config.distributed import DistributedConfig

    cfg = DistributedConfig(zero_lvl=1)
    assert cfg.sharding == "dp" and cfg.zero_stage == 1
    spec = cfg.resolve_sharding(8)
    assert spec.dp == 8 and spec.zero_stage == 1
    # explicit zero_stage wins over the zero_lvl mapping
    cfg0 = DistributedConfig(zero_lvl=1, zero_stage=0)
    assert cfg0.resolve_sharding(8).zero_stage == 0
    cfgb = DistributedConfig(sharding="dp", bucket_mb=16)
    assert cfgb.resolve_sharding(8).bucket_mb == 16.0
    with pytest.raises(ValueError):
        DistributedConfig(zero_stage=3)


def test_planner_memory_bound_raises_zero_before_batch():
    from maggy_tpu.autopilot.diagnose import Diagnosis
    from maggy_tpu.autopilot.plan import Planner

    diag = Diagnosis(
        bottleneck="memory_bound", scope="train",
        evidence={}, shares={}, reason="hbm pressure",
    )
    moves = Planner().plan_all(
        diag, {"train.zero_stage": 0, "train.batch_size": 32}
    )
    assert moves[0].knob == "train.zero_stage" and moves[0].value == 1
    assert moves[1].knob == "train.batch_size" and moves[1].value == 16
    # already sharded -> no zero move, batch shrink leads
    moves1 = Planner().plan_all(
        diag, {"train.zero_stage": 1, "train.batch_size": 32}
    )
    assert [m.knob for m in moves1][0] == "train.batch_size"


# ------------------------------------------------------ eligibility / modes


def _batch(cfg, seed=3, batch=8, seq=16):
    return next(synthetic_lm_batches(cfg.vocab_size, batch, seq, seed=seed))


def test_overlap_fallback_warns_once_on_pp_and_fsdp(monkeypatch):
    monkeypatch.setattr(pipeline_adapter, "_overlap_fallback_warned", False)
    cfg = DecoderConfig.tiny()
    model = Decoder(cfg)
    ctx = TrainContext.create(ShardingSpec(pp=2, dp=4))
    tr = ctx.trainer(model, optax.adamw(1e-3), bucket_mb=4)
    with pytest.warns(UserWarning, match="unbucketed"):
        mode, _, _ = tr._overlap_mode()
    assert mode == "off"
    # one-time: a second ineligible trainer stays silent
    ctx2 = TrainContext.create("fsdp")
    tr2 = ctx2.trainer(model, optax.adamw(1e-3), zero_stage=1)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert tr2._overlap_mode()[0] == "off"
    assert not [w for w in rec if "unbucketed" in str(w.message)]
    # and after a reset the fsdp blocker warns with its own reason
    monkeypatch.setattr(pipeline_adapter, "_overlap_fallback_warned", False)
    tr3 = ctx2.trainer(model, optax.adamw(1e-3), zero_stage=1)
    with pytest.warns(UserWarning, match="fsdp"):
        assert tr3._overlap_mode()[0] == "off"


def test_overlap_mode_resolution():
    cfg = DecoderConfig.tiny()
    model = Decoder(cfg)
    ctx = TrainContext.create("dp")
    # nothing requested -> off, silently
    assert ctx.trainer(model, optax.adamw(1e-3))._overlap_mode()[0] == "off"
    # inf bucket_mb normalizes to unbucketed -> off (the bit-identity gate)
    tr_inf = ctx.trainer(model, optax.adamw(1e-3), bucket_mb=float("inf"))
    assert tr_inf._overlap_mode()[0] == "off"
    mode, manual, dz = ctx.trainer(
        model, optax.adamw(1e-3), bucket_mb=1
    )._overlap_mode()
    # dz is the ZeRO shard count: 1 when only bucketing is requested
    assert (mode, manual, dz) == ("bucket", ("data",), 1)
    mode, manual, dz = ctx.trainer(
        model, optax.adamw(1e-3), zero_stage=1
    )._overlap_mode()
    assert (mode, dz) == ("zero", 8)


# ------------------------------------------------------------ numerics


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_loss_parity_dense_bucketed_zero_20_steps():
    """The tentpole acceptance: on a 2-axis slice(DCN)xdata(ICI) mesh the
    bucketed and ZeRO-1 steps track the dense GSPMD loss over 20 steps, and
    bucket-vs-zero are numerically interchangeable (same reduction order)."""
    cfg = DecoderConfig.tiny()
    model = Decoder(cfg)
    ctx = TrainContext.create_sliced("dp", total_slices=2)
    batch0 = _batch(cfg)

    def run(**kw):
        tr = ctx.trainer(model, optax.adamw(3e-3), **kw)
        state = tr.make_state(jax.random.key(0), batch0)
        stream = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=7)
        losses, gnorms = [], []
        for _ in range(20):
            state, m = tr.step(state, tr.shard_batch(next(stream)))
            losses.append(float(m["loss"]))
            gnorms.append(float(m["grad_norm"]))
        return tr, state, np.array(losses), np.array(gnorms)

    dense_tr, dense_state, dense_l, dense_g = run()
    bucket_tr, _, bucket_l, bucket_g = run(bucket_mb=0.25)
    zero_tr, zero_state, zero_l, zero_g = run(zero_stage=1, bucket_mb=0.25)
    assert dense_tr._overlap_mode()[0] == "off"
    assert bucket_tr._overlap_mode()[0] == "bucket"
    assert zero_tr._overlap_mode()[0] == "zero"
    # vs dense: identical math, different reduction order -> tiny drift that
    # compounds across steps (measured ~1e-4 at step 20 on this model)
    np.testing.assert_allclose(bucket_l, dense_l, rtol=0, atol=2e-3)
    np.testing.assert_allclose(zero_l, dense_l, rtol=0, atol=2e-3)
    np.testing.assert_allclose(bucket_g, dense_g, rtol=2e-3, atol=2e-3)
    # bucket vs zero share one reduction order -> effectively identical
    np.testing.assert_allclose(zero_l, bucket_l, rtol=0, atol=1e-6)
    # ZeRO-1 state: flat bucket vectors sharded over the data axis
    from maggy_tpu.parallel.spec import AXIS_DATA

    plan = ovl.plan_buckets(zero_state.params, 0.25, pad_to=4)
    flat_leaves = [
        leaf
        for leaf in jax.tree.leaves(zero_state.opt_state)
        if getattr(leaf, "ndim", None) == 1
        and leaf.shape[0] in plan.padded_sizes
    ]
    assert flat_leaves, "zero opt state holds no flat bucket vectors"
    for leaf in flat_leaves:
        assert leaf.sharding.spec == jax.sharding.PartitionSpec(AXIS_DATA)


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_zero0_inf_bucket_bit_identical_to_dense():
    """zero_stage=0 + bucket_mb=inf resolves to the dense path itself, so
    the numerics are bit-compatible by construction — asserted by running
    both and comparing exactly."""
    cfg = DecoderConfig.tiny()
    model = Decoder(cfg)
    ctx = TrainContext.create("dp")
    batch0 = _batch(cfg)
    results = []
    for kw in ({}, {"zero_stage": 0, "bucket_mb": float("inf")}):
        tr = ctx.trainer(model, optax.adamw(3e-3), **kw)
        assert tr._overlap_mode()[0] == "off"
        state = tr.make_state(jax.random.key(0), batch0)
        stream = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=5)
        losses = []
        for _ in range(3):
            state, m = tr.step(state, tr.shard_batch(next(stream)))
            losses.append(float(m["loss"]))
        results.append((losses, jax.tree.map(np.asarray, state.params)))
    assert results[0][0] == results[1][0]  # bitwise-equal losses
    assert jax.tree.all(
        jax.tree.map(
            lambda a, b: bool(np.array_equal(a, b)),
            results[0][1], results[1][1],
        )
    )


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_zero1_shrinks_opt_bytes_per_device():
    """AOT accounting from shapes+shardings alone (no compile): ZeRO-1 cuts
    optimizer bytes per device by ~1/data_width (exactly 1/8 up to padding
    and the unsharded count scalar)."""
    cfg = DecoderConfig.tiny()
    model = Decoder(cfg)
    ctx = TrainContext.create("dp")
    batch = _batch(cfg)

    def opt_bytes(tr):
        shardings = tr.state_shardings_for(batch)
        abstract = jax.eval_shape(
            tr._init_fn(), jax.random.key(0), batch["tokens"]
        )
        return ovl.opt_state_bytes_per_device(abstract, shardings)

    dense = opt_bytes(ctx.trainer(model, optax.adamw(1e-3)))
    zero = opt_bytes(
        ctx.trainer(model, optax.adamw(1e-3), zero_stage=1, bucket_mb=0.25)
    )
    assert zero < dense
    assert zero / dense <= 1 / 8 + 0.10


# ----------------------------------------------------------- checkpoints


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_checkpoint_zero1_restores_into_dense(tmp_path):
    """Save under ZeRO-1 (flat sharded state), restore into a zero_stage=0
    trainer: warn-and-reshard converts the layout and the optimizer state is
    equal element-for-element (padding dropped)."""
    cfg = DecoderConfig.tiny()
    model = Decoder(cfg)
    ctx = TrainContext.create("dp")
    batch = _batch(cfg)
    zt = ctx.trainer(model, optax.adamw(3e-3), zero_stage=1, bucket_mb=0.25)
    state = zt.make_state(jax.random.key(0), batch)
    state, _ = zt.step(state, zt.shard_batch(batch))
    ck = Checkpointer(str(tmp_path), async_save=False)
    try:
        ck.save(int(state.step), state, meta=zt.checkpoint_meta())
        ck.wait()
        assert ck.saved_meta()["zero"] == {
            "stage": 1, "bucket_mb": 0.25, "shards": 8,
        }
        dt = ctx.trainer(model, optax.adamw(3e-3))
        tmpl = dt.make_state(jax.random.key(1), batch)
        with pytest.warns(UserWarning, match="ZeRO-1"):
            restored = restore_zero_compat(
                ck, tmpl, live_meta=dt.checkpoint_meta()
            )
        plan = ovl.plan_buckets(state.params, 0.25, pad_to=8)
        dense_as_flat = ovl.flatten_opt_state(
            jax.tree.map(np.asarray, restored.opt_state), plan,
            restored.params,
        )
        assert jax.tree.all(
            jax.tree.map(
                lambda a, b: bool(np.array_equal(np.asarray(a), np.asarray(b))),
                jax.tree.map(np.asarray, state.opt_state), dense_as_flat,
            )
        )
        assert jax.tree.all(
            jax.tree.map(
                lambda a, b: bool(np.array_equal(np.asarray(a), np.asarray(b))),
                state.params, restored.params,
            )
        )
        assert int(restored.step) == int(state.step)
    finally:
        ck.close()


@pytest.mark.slow
@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_checkpoint_zero1_restores_at_different_width(tmp_path):
    """Save ZeRO-1 over 8 shards, restore ZeRO-1 over 2 (simulated world-size
    change): the re-bucketing path rebuilds padding for the new width, state
    matches reflatten_opt_state exactly, and training continues."""
    from maggy_tpu import telemetry

    cfg = DecoderConfig.tiny()
    model = Decoder(cfg)
    batch = _batch(cfg)
    ctx = TrainContext.create("dp")
    zt = ctx.trainer(model, optax.adamw(3e-3), zero_stage=1, bucket_mb=0.25)
    state = zt.make_state(jax.random.key(0), batch)
    state, _ = zt.step(state, zt.shard_batch(batch))
    ck = Checkpointer(str(tmp_path), async_save=False)
    try:
        ck.save(int(state.step), state, meta=zt.checkpoint_meta())
        ck.wait()
        ctx2 = TrainContext.create(
            ShardingSpec(dp=2), devices=jax.devices()[:2]
        )
        zt2 = ctx2.trainer(
            model, optax.adamw(3e-3), zero_stage=1, bucket_mb=0.25
        )
        tmpl = zt2.make_state(jax.random.key(2), batch)
        tel = telemetry.Telemetry(worker="test-overlap")
        with telemetry.current(tel):
            with pytest.warns(UserWarning, match="shards=8"):
                restored = restore_zero_compat(
                    ck, tmpl, live_meta=zt2.checkpoint_meta()
                )
        counters = tel.snapshot().get("counters", {})
        assert counters.get("resilience.ckpt_zero_reshards", 0) == 1
        plan8 = ovl.plan_buckets(state.params, 0.25, pad_to=8)
        plan2 = ovl.plan_buckets(state.params, 0.25, pad_to=2)
        expect = ovl.reflatten_opt_state(
            jax.tree.map(np.asarray, state.opt_state), plan8, plan2,
            state.params,
        )
        assert jax.tree.all(
            jax.tree.map(
                lambda a, b: bool(np.array_equal(np.asarray(a), np.asarray(b))),
                expect, jax.tree.map(np.asarray, restored.opt_state),
            )
        )
        # the narrower trainer keeps stepping from the converted state
        restored, m = zt2.step(restored, zt2.shard_batch(batch))
        assert np.isfinite(m["loss"])
    finally:
        ck.close()
