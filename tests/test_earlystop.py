"""Median stopping rule tests (reference earlystop/medianrule.py:27-60 semantics)."""

from maggy_tpu import Trial
from maggy_tpu.earlystop import MedianStoppingRule, NoStoppingRule


def finalized_trial(metrics):
    t = Trial({"id": repr(metrics)})
    for s, m in enumerate(metrics):
        t.append_metric(m, step=s)
    t.finalize(metrics[-1])
    return t


def running_trial(metrics):
    t = Trial({"id": "running" + repr(metrics)})
    t.begin()
    for s, m in enumerate(metrics):
        t.append_metric(m, step=s)
    return t


def test_median_rule_stops_bad_trial_max():
    final = [finalized_trial([0.5, 0.6, 0.7]), finalized_trial([0.4, 0.5, 0.6])]
    bad = running_trial([0.1, 0.1, 0.1])
    good = running_trial([0.9, 0.9, 0.9])
    out = MedianStoppingRule.earlystop_check(
        {"bad": bad, "good": good}, final, direction="max"
    )
    assert out == ["bad"]


def test_median_rule_direction_min():
    final = [finalized_trial([0.5, 0.4]), finalized_trial([0.6, 0.5])]
    bad = running_trial([2.0, 2.0])  # high loss -> stop under min
    good = running_trial([0.1, 0.1])
    out = MedianStoppingRule.earlystop_check(
        {"bad": bad, "good": good}, final, direction="min"
    )
    assert out == ["bad"]


def test_median_rule_no_finalized_no_stop():
    assert (
        MedianStoppingRule.earlystop_check({"x": running_trial([0.0])}, [], "max") == []
    )


def test_median_rule_ignores_metricless_running_trial():
    final = [finalized_trial([0.5])]
    t = Trial({"fresh": 1})
    assert MedianStoppingRule.earlystop_check({"fresh": t}, final, "max") == []


def test_nostop():
    final = [finalized_trial([0.5])]
    assert NoStoppingRule.earlystop_check({"x": running_trial([0.0])}, final, "max") == []
