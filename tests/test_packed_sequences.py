"""Packed sequences (segment_ids) through every attention path (VERDICT r3
item 5, SURVEY §5.7): blockwise, the XLA ring and Ulysses on the sp=4 mesh,
the Pallas flash kernel (interpret machine), and the Decoder/Trainer
end-to-end. The ground truth everywhere: packed attention over segments ==
dense attention run on each segment separately."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from maggy_tpu.models.transformer import default_attention
from maggy_tpu.ops.attention import blockwise_attention
from maggy_tpu.ops.flash import flash_attention
from maggy_tpu.parallel.ringattention import ring_attention
from maggy_tpu.parallel.ulysses import ulysses_attention
from maggy_tpu.util import set_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the 8-device CPU mesh"
)


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


def _packed(B=2, S=128, H=4, KH=2, D=16, n_segs=3, seed=0):
    ks = jax.random.split(jax.random.key(seed), 4)
    q = jax.random.normal(ks[0], (B, S, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, KH, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, KH, D), jnp.float32)
    # contiguous segments with uneven boundaries
    bounds = np.sort(
        np.random.default_rng(seed).choice(
            np.arange(8, S - 8), size=n_segs - 1, replace=False
        )
    )
    seg_row = np.zeros(S, np.int32)
    for b in bounds:
        seg_row[b:] += 1
    seg = jnp.asarray(np.stack([seg_row, (seg_row + 1) % n_segs + 10]))[:B]
    return q, k, v, seg


def _segwise_dense(q, k, v, seg, causal=True):
    """Ground truth: dense attention run on each segment independently."""
    out = np.zeros(q.shape, np.float32)
    for b in range(q.shape[0]):
        for s in np.unique(np.asarray(seg[b])):
            idx = np.where(np.asarray(seg[b]) == s)[0]
            o = default_attention(
                q[b : b + 1, idx], k[b : b + 1, idx], v[b : b + 1, idx],
                causal=causal,
            )
            out[b, idx] = np.asarray(o)[0]
    return out


def test_blockwise_segment_parity():
    q, k, v, seg = _packed()
    ref = _segwise_dense(q, k, v, seg)
    out = blockwise_attention(q, k, v, causal=True, segment_ids=seg, block_k=32)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)
    # and default_attention's own segment mask agrees
    out2 = default_attention(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out2), ref, atol=2e-5)


def test_xla_ring_segment_parity_sp4():
    q, k, v, seg = _packed()
    ref = _segwise_dense(q, k, v, seg)
    mesh = _mesh(4)
    with set_mesh(mesh):
        out = ring_attention(
            q, k, v, mesh=mesh, causal=True, segment_ids=seg, impl="xla"
        )
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_xla_ring_segment_grads_flow():
    """Cross-segment grads must be exactly zero; within-segment nonzero."""
    mesh = _mesh(2)
    q, k, v, seg = _packed(B=1, S=32, H=2, KH=2, D=8, n_segs=2, seed=1)

    def loss(q, k, v):
        out = ring_attention(
            q, k, v, mesh=mesh, causal=True, segment_ids=seg, impl="xla"
        )
        # loss reads only segment-0 outputs
        m = (seg[0] == np.asarray(seg[0])[0]).astype(np.float32)
        return (out[0] * m[:, None, None] ** 1).sum()

    with set_mesh(mesh):
        gk = jax.grad(loss, argnums=1)(q, k, v)
    seg0 = np.asarray(seg[0]) == np.asarray(seg[0])[0]
    assert float(jnp.abs(gk[0, ~seg0]).max()) == 0.0
    assert float(jnp.abs(gk[0, seg0]).max()) > 0.0


def test_ulysses_segment_parity_sp4():
    q, k, v, seg = _packed(H=4, KH=4)  # ulysses: n | H
    ref = _segwise_dense(q, k, v, seg)
    mesh = _mesh(4)
    with set_mesh(mesh):
        out = ulysses_attention(
            q, k, v, mesh=mesh, causal=True, segment_ids=seg
        )
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_flash_kernel_segment_parity_and_grads():
    """The Pallas kernel path (interpret machine) with in-kernel segment
    masking: forward parity AND gradient parity vs the dense reference."""
    q, k, v, seg = _packed(B=2, S=64, H=4, KH=2, D=128, n_segs=2, seed=2)
    ref = _segwise_dense(q, k, v, seg)
    out = flash_attention(
        q, k, v, causal=True, segment_ids=seg, block_q=16, block_k=16,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, causal=True, segment_ids=seg, block_q=16, block_k=16,
            interpret=True,
        )
        return (o * jnp.cos(o)).sum()

    def loss_dense(q, k, v):
        o = default_attention(q, k, v, causal=True, segment_ids=seg)
        return (o * jnp.cos(o)).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_moe_decoder_accepts_segment_ids():
    """The MoE family threads segment_ids to its attention like Decoder:
    output must differ from the unsegmented forward (the mask bites) and
    match a two-forward per-segment reference on the first segment."""
    from maggy_tpu.models import MoEConfig, MoEDecoder

    cfg = MoEConfig.tiny_moe()
    model = MoEDecoder(cfg)
    rng = np.random.default_rng(0)
    B, S = 2, 32
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32)
    seg = np.zeros((B, S), np.int32)
    seg[:, S // 2 :] = 1
    params = model.init(jax.random.key(0), tokens)["params"]
    packed = model.apply({"params": params}, tokens, None, jnp.asarray(seg))
    plain = model.apply({"params": params}, tokens)
    assert not np.allclose(np.asarray(packed), np.asarray(plain), atol=1e-4)
    # first segment sees only itself: equals a forward on just that slice
    ref = model.apply({"params": params}, tokens[:, : S // 2])
    np.testing.assert_allclose(
        np.asarray(packed[:, : S // 2]), np.asarray(ref), atol=2e-2
    )


def test_decoder_trainer_packed_end_to_end():
    """Packed batch {tokens, positions, segment_ids} through the Trainer on
    the sp mesh: segment_ids reach ring attention, positions restart per
    segment, the LM loss skips boundary targets, and loss decreases."""
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.ringattention import make_ring_attention
    from maggy_tpu.parallel.spec import ShardingSpec
    from maggy_tpu.train import TrainContext

    ctx = TrainContext.create(ShardingSpec(sp=4, dp=2))
    cfg = DecoderConfig.tiny(attention_fn=make_ring_attention(ctx.mesh))
    rng = np.random.default_rng(0)
    B, S = 4, 64
    tokens = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    seg = np.zeros((B, S), np.int32)
    seg[:, S // 2 :] = 1  # two packed docs per row
    pos = np.concatenate(
        [np.arange(S // 2), np.arange(S - S // 2)]
    )[None].repeat(B, 0).astype(np.int32)
    batch = {"tokens": tokens, "positions": pos, "segment_ids": seg}

    trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-2))
    state = trainer.make_state(jax.random.key(0), batch)
    losses = []
    for _ in range(5):
        state, m = trainer.step(state, trainer.shard_batch(batch))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_packed_side_inputs_seq_sharded_no_remat(capfd):
    """VERDICT r4 item 5: on an sp mesh the packed side inputs must be
    PLACED (batch, seq) by shard_batch, so XLA never has to involuntarily
    rematerialize them per step. Oracle: XLA's own 'Involuntary full
    rematerialization' SPMD warning — absent with the trainer's placement,
    present (positive control) when the same inputs are forced batch-only."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.ringattention import make_ring_attention
    from maggy_tpu.parallel.spec import ShardingSpec
    from maggy_tpu.train import TrainContext

    # the warning fires at partition time only — a persistent-cache hit
    # would silently skip it and blind both arms of the test
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        B, S = 4, 128
        ctx = TrainContext.create(ShardingSpec(fsdp=2, sp=4))
        cfg = DecoderConfig.tiny(attention_fn=make_ring_attention(ctx.mesh))
        trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
        rng = np.random.default_rng(0)
        seg = np.zeros((B, S), np.int32)
        seg[:, S // 2:] = 1
        pos = (
            np.concatenate([np.arange(S // 2), np.arange(S - S // 2)])[None]
            .repeat(B, 0)
            .astype(np.int32)
        )
        batch = {
            "tokens": rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32),
            "positions": pos,
            "segment_ids": seg,
        }
        state = trainer.make_state(jax.random.key(0), batch)
        step = trainer._build_train_step()

        sb = trainer.shard_batch(batch)
        assert sb["segment_ids"].sharding.spec == P(("data", "fsdp"), "seq")
        assert sb["positions"].sharding.spec == P(("data", "fsdp"), "seq")

        capfd.readouterr()  # drain
        with trainer.mesh:
            step.lower(state, sb).compile()
        err = capfd.readouterr().err
        assert "Involuntary full rematerialization" not in err, err[-1500:]

        # positive control: the batch-only placement this replaced DOES trip
        # the warning — proving the oracle detects the regression
        bo = NamedSharding(trainer.mesh, P(("data", "fsdp")))
        sb_old = dict(sb)
        sb_old["segment_ids"] = jax.device_put(seg, bo)
        sb_old["positions"] = jax.device_put(pos, bo)
        with trainer.mesh:
            step.lower(state, sb_old).compile()
        err = capfd.readouterr().err
        assert "Involuntary full rematerialization" in err

        # numerics are placement-independent (fresh states: step donates)
        _, m_new = trainer.step(state, sb)
        state2 = trainer.make_state(jax.random.key(0), batch)
        _, m_old = trainer.step(state2, sb_old)
        assert abs(float(m_new["loss"]) - float(m_old["loss"])) < 1e-5
    finally:
        jax.config.update("jax_enable_compilation_cache", True)


def test_padded_packed_row_needs_loss_mask():
    """ADVICE r4 / docs 'Padding convention': a trailing pad region that
    shares a segment id still attends within itself and contributes
    next-token loss — `loss_mask` is what removes it. Locks both facts: the
    unmasked padded loss differs from the true loss; the masked one matches
    the unpadded row exactly."""
    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train.trainer import lm_loss_fn

    cfg = DecoderConfig.tiny()
    rng = np.random.default_rng(2)
    S, PAD = 24, 8
    doc = rng.integers(1, cfg.vocab_size, S).astype(np.int32)
    model = Decoder(cfg)
    variables = model.init(jax.random.key(1), jnp.asarray(doc[None]))

    # unpadded reference
    jb_ref = {"tokens": jnp.asarray(doc[None])}
    ref = float(lm_loss_fn(model.apply(variables, jb_ref["tokens"]), jb_ref))

    # padded row: pad gets its OWN segment id (so it cannot attend into the
    # document), but without a loss_mask its intra-pad targets still count
    padded = np.concatenate([doc, np.zeros(PAD, np.int32)])
    seg = np.concatenate([np.zeros(S), np.ones(PAD)]).astype(np.int32)
    pos = np.concatenate([np.arange(S), np.arange(PAD)]).astype(np.int32)
    jb = {
        "tokens": jnp.asarray(padded[None]),
        "segment_ids": jnp.asarray(seg[None]),
        "positions": jnp.asarray(pos[None]),
    }
    logits = model.apply(variables, jb["tokens"], jb["positions"], jb["segment_ids"])
    unmasked = float(lm_loss_fn(logits, jb))
    assert abs(unmasked - ref) > 1e-3  # pad leaks into the objective

    mask = np.concatenate([np.ones(S), np.zeros(PAD)]).astype(np.float32)
    masked = float(lm_loss_fn(logits, {**jb, "loss_mask": jnp.asarray(mask[None])}))
    np.testing.assert_allclose(masked, ref, atol=2e-3)  # mask restores truth
