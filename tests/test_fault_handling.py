"""Failure-detection paths: lost-trial reassignment on worker restart
(reference rpc.py:415-437), experiment state metadata, stale-worker abort."""

import json
import os
import time

import pytest

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.core import rpc
from maggy_tpu.core.driver.hpo import HyperparameterOptDriver
from maggy_tpu.trial import Trial


def make_driver(tmp_env, num_trials=4, **kwargs):
    cfg = HyperparameterOptConfig(
        num_trials=num_trials,
        optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
        num_executors=2,
        es_policy="none",
        hb_interval=0.05,
        seed=0,
        **kwargs,
    )
    return HyperparameterOptDriver(cfg, "app_fault", 1)


def test_lost_trial_requeued_and_partition_rescheduled(tmp_env):
    """A worker re-registration (new attempt nonce) with an in-flight trial
    must requeue that trial (transient loss, docs/resilience.md) and hand
    the partition a fresh one meanwhile."""
    driver = make_driver(tmp_env)
    driver.server = driver._make_server()
    driver._register_msg_callbacks()

    # initial registration + assignment
    driver.server.reservations.register(0, {"attempt": "a1"})
    driver._digest_reg({"type": "REG", "partition_id": 0, "reregistered": False})
    first = driver.server.reservations.get_assignment(0)
    assert first is not None
    assert driver.trial_store[first].status == Trial.SCHEDULED

    # same worker instance retries REG -> NOT a restart
    assert not driver.server.reservations.register(0, {"attempt": "a1"})

    # a new instance (restart) takes the partition
    assert driver.server.reservations.register(0, {"attempt": "a2"})
    driver._digest_reg({"type": "REG", "partition_id": 0, "reregistered": True})

    # the lost trial sits in the retry queue (NOT terminal ERROR) with its
    # retry counter bumped...
    queued = [t for _ready, t in driver._retry_queue]
    assert [t.trial_id for t in queued] == [first]
    assert queued[0].status == Trial.PENDING
    assert queued[0].info_dict["retries"] == 1
    assert not driver.final_store
    # ...while the restarted partition immediately serves a different trial
    second = driver.server.reservations.get_assignment(0)
    assert second is not None and second != first


def test_lost_trial_error_after_retry_budget(tmp_env):
    """trial_retries=0 restores the terminal-ERROR behavior: the loss is
    persisted and counted against the budget."""
    driver = make_driver(tmp_env, trial_retries=0)
    driver.server = driver._make_server()
    driver._register_msg_callbacks()

    driver.server.reservations.register(0, {"attempt": "a1"})
    driver._digest_reg({"type": "REG", "partition_id": 0, "reregistered": False})
    first = driver.server.reservations.get_assignment(0)
    assert first is not None

    assert driver.server.reservations.register(0, {"attempt": "a2"})
    driver._digest_reg({"type": "REG", "partition_id": 0, "reregistered": True})

    lost = [t for t in driver.final_store if t.trial_id == first]
    assert len(lost) == 1 and lost[0].status == Trial.ERROR
    assert not driver._retry_queue
    second = driver.server.reservations.get_assignment(0)
    assert second is not None and second != first
    # the lost trial persisted like any other
    assert os.path.exists(
        os.path.join(tmp_env.experiment_dir("app_fault", 1), first, "trial.json")
    )


def test_experiment_state_lifecycle(tmp_env):
    def train(hparams):
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=2, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0, 1])),
        num_executors=1, es_policy="none", hb_interval=0.05,
    )
    experiment.lagom(train, cfg)
    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    state = json.load(open(os.path.join(exp_dir, "state.json")))
    assert state["state"] == "FINISHED"

    with pytest.raises(RuntimeError):
        experiment.lagom(lambda hparams: (_ for _ in ()).throw(RuntimeError("x")), cfg)
    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    state = json.load(open(os.path.join(exp_dir, "state.json")))
    assert state["state"] == "FAILED"


def test_log_verb_serves_progress(tmp_env):
    """The LOG channel (sparkmagic/jupyter monitor parity, rpc.py:490-502)."""
    import threading

    progress_seen = []

    def train(hparams, reporter):
        reporter.log("working hard")
        time.sleep(0.2)
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=3, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0, 1])),
        num_executors=1, es_policy="none", hb_interval=0.05, seed=1,
    )

    def monitor():
        deadline = time.time() + 20
        client = None
        while time.time() < deadline:
            driver = experiment.CURRENT_DRIVER
            if driver is not None and driver.server is not None and driver.server.port:
                try:
                    client = rpc.Client(
                        (driver.server.host, driver.server.port), 99, driver.server.secret
                    )
                    break
                except Exception:
                    time.sleep(0.05)
            time.sleep(0.02)
        if client is None:
            return
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                reply = client._request({"type": "LOG"})
            except Exception:
                break
            if reply.get("progress"):
                progress_seen.append(reply["progress"])
            time.sleep(0.05)
        client.stop()

    t = threading.Thread(target=monitor, daemon=True)
    t.start()
    experiment.lagom(train, cfg)
    t.join(timeout=2)
    assert progress_seen  # monitor observed live progress strings
    assert any("3" in p for p in progress_seen)
