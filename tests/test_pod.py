"""Pod-mode control plane: a real second OS process connects to the driver
over TCP, registers, passes the reservation barrier, trains its own copy of
the train_fn, and its FINAL is aggregated — the TPU-VM pod execution model
(every host runs the same script) exercised on localhost."""

import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from maggy_tpu import experiment
from maggy_tpu.config import DistributedConfig

pytestmark = pytest.mark.slow  # subprocess/multi-process tier

WORKER_SCRIPT = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from maggy_tpu import experiment
    from maggy_tpu.config import DistributedConfig

    def train(hparams, reporter, ctx):
        reporter.broadcast(1.0, step=0)
        return {{"metric": float(hparams["base"]) + 1.0}}

    result = experiment.lagom(
        train,
        DistributedConfig(
            hparams={{"base": 10.0}},
            num_executors=2,
            sharding="dp",
            data_plane="local",
            hb_interval=0.05,
        ),
    )
    print("WORKER-DONE", result)
    """
).format(repo=os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))


def test_pod_two_process_training(tmp_env, tmp_path):
    result_holder = {}

    def train(hparams, reporter, ctx):
        reporter.broadcast(1.0, step=0)
        return {"metric": float(hparams["base"]) + 1.0}

    config = DistributedConfig(
        hparams={"base": 10.0},
        num_executors=2,
        sharding="dp",
        data_plane="local",
        driver_addr="127.0.0.1:auto",  # placeholder: flags pod mode for the driver
        hb_interval=0.05,
    )

    def run_driver():
        result_holder["result"] = experiment.lagom(train, config)

    t = threading.Thread(target=run_driver)
    t.start()

    # discover the live driver's port + secret (what a pod launcher reads)
    deadline = time.time() + 30
    driver = None
    while time.time() < deadline:
        driver = experiment.CURRENT_DRIVER
        if driver is not None and driver.server is not None and driver.server.port:
            break
        time.sleep(0.05)
    assert driver is not None and driver.server is not None, "driver never started"
    assert driver.pod_mode

    script = tmp_path / "worker.py"
    script.write_text(WORKER_SCRIPT)
    env = dict(os.environ)
    env.update(
        {
            "MAGGY_TPU_ROLE": "worker",
            "MAGGY_TPU_DRIVER": f"127.0.0.1:{driver.server.port}",
            "MAGGY_TPU_SECRET": driver.server.secret,
            "MAGGY_TPU_PARTITION": "1",
            "MAGGY_TPU_LOG_ROOT": str(tmp_path / "worker_logs"),
        }
    )
    proc = subprocess.run(
        [sys.executable, str(script)], env=env, capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "WORKER-DONE" in proc.stdout
    assert "'role': 'worker'" in proc.stdout

    t.join(timeout=60)
    assert not t.is_alive(), "driver did not finish"
    result = result_holder["result"]
    assert result["num_workers"] == 2
    assert result["metric"] == pytest.approx(11.0)  # both workers returned 11.0
