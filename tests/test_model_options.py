"""Decoder config options not covered elsewhere: tied embeddings, logit
softcap, unrolled layers; GCS env gating."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models import Decoder, DecoderConfig


def test_tied_embeddings_reduce_params():
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    tied = Decoder(DecoderConfig.tiny(tie_embeddings=True))
    untied = Decoder(DecoderConfig.tiny(tie_embeddings=False))
    v_tied = tied.init(jax.random.key(0), tokens)
    v_untied = untied.init(jax.random.key(0), tokens)
    n = lambda v: sum(x.size for x in jax.tree.leaves(v))  # noqa: E731
    assert n(v_tied) < n(v_untied)
    assert "lm_head" not in v_tied["params"]
    out = tied.apply(v_tied, tokens)
    assert np.isfinite(np.asarray(out)).all()


def test_logits_softcap_bounds_logits():
    cfg = DecoderConfig.tiny(logits_softcap=5.0)
    model = Decoder(cfg)
    tokens = jnp.asarray(np.arange(16)[None, :], dtype=jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    logits = model.apply(variables, tokens)
    assert float(jnp.abs(logits).max()) <= 5.0 + 1e-5


def test_gcs_env_gated_without_fsspec(monkeypatch):
    import builtins

    from maggy_tpu.core.env.gcs import GcsEnv

    real_import = builtins.__import__

    def no_fsspec(name, *args, **kwargs):
        if name == "fsspec":
            raise ImportError("fsspec unavailable")
        return real_import(name, *args, **kwargs)

    monkeypatch.setattr(builtins, "__import__", no_fsspec)
    env = GcsEnv("gs://bucket")
    with pytest.raises(RuntimeError, match="fsspec"):
        env.exists("gs://bucket/x")


def test_env_selection(monkeypatch, tmp_path):
    from maggy_tpu.core import env as env_mod

    env_mod.set_instance(None)
    monkeypatch.setenv("MAGGY_TPU_LOG_ROOT", "gs://bucket/experiments")
    from maggy_tpu.core.env.gcs import GcsEnv

    assert isinstance(env_mod.get_instance(), GcsEnv)
    env_mod.set_instance(None)
    monkeypatch.setenv("MAGGY_TPU_LOG_ROOT", str(tmp_path))
    from maggy_tpu.core.env.base import BaseEnv

    inst = env_mod.get_instance()
    assert isinstance(inst, BaseEnv) and not isinstance(inst, GcsEnv)
    env_mod.set_instance(None)
