"""Chaos-harness acceptance demos (ISSUE 4, docs/resilience.md): every
recovery path driven end-to-end on CPU by the deterministic fault injector —
HPO trial requeue after a mid-trial worker kill, fit(resume="auto")
round-trip matching an uninterrupted run, distributed elastic restart, the
preemption save, and the checkpoint-restore fallback."""

import glob
import json
import os

import numpy as np
import pytest

from maggy_tpu import Searchspace, experiment, telemetry
from maggy_tpu.config import DistributedConfig, HyperparameterOptConfig
from maggy_tpu.resilience import chaos as chaos_mod
from maggy_tpu.resilience import preemption


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos_mod.reset()
    preemption.clear()
    yield
    chaos_mod.reset()
    preemption.clear()


def _exported_counters(exp_dir):
    """Merge counters from every exported telemetry snapshot under exp_dir."""
    merged = {}
    for path in glob.glob(os.path.join(exp_dir, "telemetry", "*.jsonl")):
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") == "snapshot":
                    for k, v in (rec.get("counters") or {}).items():
                        merged[k] = merged.get(k, 0) + v
    return merged


def test_hpo_worker_kill_mid_trial_completes_budget(tmp_env):
    """ACCEPTANCE: an HPO run with a worker killed mid-trial completes its
    full trial budget with the lost trial retried (not ERROR), and
    resilience.* counters land in the exported telemetry."""
    chaos_mod.install(chaos_mod.Chaos.parse("kill:worker=1"))

    def train(hparams, reporter):
        ch = chaos_mod.get()
        if ch is not None:
            ch.kill(reporter.partition_id)  # fires once, on worker 1
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=6,
        optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
        num_executors=2,
        es_policy="none",
        hb_interval=0.05,
        seed=3,
        retry_backoff=0.05,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 6  # full budget despite the kill
    assert result["errors"] == 0  # the lost trial was RETRIED, not ERROR
    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    counters = _exported_counters(exp_dir)
    assert counters.get("resilience.trials_requeued", 0) >= 1
    assert counters.get("resilience.worker_deaths", 0) >= 1


def test_hpo_deterministic_failure_still_fails_fast(tmp_env):
    """A train_fn exception is DETERMINISTIC: no retry burn-down — the run
    aborts like before."""

    def train(hparams):
        raise ValueError("broken train_fn")

    cfg = HyperparameterOptConfig(
        num_trials=4, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0, 1])),
        num_executors=1, es_policy="none", hb_interval=0.05,
    )
    with pytest.raises(RuntimeError, match="broken train_fn"):
        experiment.lagom(train, cfg)


def _tiny_setup(seed=5):
    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.data import synthetic_lm_batches

    cfg = DecoderConfig.tiny()
    ctx = TrainContext.create("dp")
    trainer = ctx.trainer(Decoder(cfg), optax.adamw(3e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=seed)
    state = trainer.make_state(jax.random.key(0), next(
        synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=seed)
    ))
    return trainer, state, data


def test_fit_resume_auto_matches_uninterrupted(tmp_path):
    """ACCEPTANCE (training tier): kill at step K -> fit(resume="auto") ->
    the final loss matches an uninterrupted run exactly (same data stream,
    fast-forwarded)."""
    from maggy_tpu.exceptions import WorkerLost
    from maggy_tpu.train.checkpoint import Checkpointer

    # uninterrupted reference
    trainer, state, data = _tiny_setup()
    state, ref = trainer.fit(state, data, num_steps=8)
    assert int(state.step) == 8

    # run 2: killed at step 4 by the chaos harness, then resumed
    chaos_mod.install(chaos_mod.Chaos.parse("kill:step=4"))
    trainer2, state2, data2 = _tiny_setup()
    ckpt = Checkpointer(str(tmp_path / "ck"), async_save=False)
    with pytest.raises(WorkerLost):
        trainer2.fit(state2, data2, num_steps=8, checkpointer=ckpt,
                     checkpoint_every=2)
    assert ckpt.latest_step() == 4

    tel = telemetry.Telemetry(worker="t", role="test")
    with telemetry.current(tel):
        trainer3, state3, data3 = _tiny_setup()  # fresh state AND data
        state3, out = trainer3.fit(
            state3, data3, num_steps=8, checkpointer=ckpt,
            checkpoint_every=2, resume="auto",
        )
    ckpt.close()
    assert int(state3.step) == 8
    assert out["resumed_from"] == 4.0
    assert tel.snapshot()["counters"]["resilience.auto_resumes"] == 1
    np.testing.assert_allclose(out["loss"], ref["loss"], rtol=1e-5)


def test_fit_preemption_saves_and_resumes(tmp_path):
    """SIGTERM/preemption notice -> one final synchronous save at the current
    step and an early return; resume="auto" finishes the budget."""
    from maggy_tpu.train.checkpoint import Checkpointer

    trainer, state, data = _tiny_setup(seed=9)

    def noisy(src, notice_after):
        n = 0
        for batch in src:
            yield batch
            n += 1
            if n == notice_after:
                preemption.request()

    ckpt = Checkpointer(str(tmp_path / "ck"), async_save=False)
    tel = telemetry.Telemetry(worker="t", role="test")
    with telemetry.current(tel):
        # prefetch=0: the notice fires as a loader side effect, so its
        # arrival step is only deterministic on the synchronous input path
        # (the prefetcher would pull — and trigger — it a couple of steps
        # early); prefetch interplay is covered in test_prefetch.py
        state, out = trainer.fit(
            state, noisy(data, 3), num_steps=6, checkpointer=ckpt, prefetch=0,
        )
    assert out["preempted"] == 1.0
    # the notice arrives while step 4's batch is being fetched, so fit honors
    # it at the NEXT step boundary: 4 steps ran, then one synchronous save
    assert int(state.step) == 4
    assert ckpt.latest_step() == 4
    assert tel.snapshot()["counters"]["resilience.preempt_saves"] == 1

    preemption.clear()
    trainer2, state2, data2 = _tiny_setup(seed=9)
    state2, out2 = trainer2.fit(
        state2, data2, num_steps=6, checkpointer=ckpt, resume="auto"
    )
    ckpt.close()
    assert int(state2.step) == 6
    assert out2["resumed_from"] == 4.0


def test_distributed_elastic_restart_matches_uninterrupted(tmp_env):
    """ACCEPTANCE (distributed tier): a distributed run killed at step K
    resumes via resume="auto" + elastic restart to the same final loss as an
    uninterrupted run, with resilience.* counters in the exported
    telemetry."""
    import jax
    import optax

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train.checkpoint import Checkpointer
    from maggy_tpu.train.data import synthetic_lm_batches

    cfg = DecoderConfig.tiny()

    def train(model, hparams, reporter, ctx, trial_dir):
        trainer = ctx.trainer(model, optax.adamw(3e-3))
        data = synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=5)
        state = trainer.make_state(jax.random.key(0), next(
            synthetic_lm_batches(cfg.vocab_size, 8, 16, seed=5)
        ))
        ckpt = Checkpointer(os.path.join(trial_dir, "ckpt"), async_save=False)
        try:
            state, metrics = trainer.fit(
                state, data, num_steps=8, checkpointer=ckpt,
                checkpoint_every=2, resume="auto",
            )
        finally:
            ckpt.close()
        return {"metric": -metrics["loss"], "loss": metrics["loss"]}

    def dconf():
        return DistributedConfig(
            module=Decoder(cfg), hparams={}, sharding="dp",
            data_plane="local", hb_interval=0.05, max_restarts=1,
        )

    # uninterrupted reference
    ref = experiment.lagom(train, dconf())

    # chaos: kill worker 0 at global step 4 — first attempt dies, the driver
    # absorbs it (elastic restart), the relaunched train_fn resumes from the
    # step-4 checkpoint and must land on the same final loss
    chaos_mod.install(chaos_mod.Chaos.parse("kill:worker=0,step=4"))
    result = experiment.lagom(train, dconf())
    assert result["num_workers"] == 1
    np.testing.assert_allclose(result["loss"], ref["loss"], rtol=1e-5)

    exp_dir = tmp_env.experiment_dir(experiment.APP_ID, experiment.RUN_ID)
    counters = _exported_counters(exp_dir)
    assert counters.get("resilience.dist_restarts", 0) == 1
    assert counters.get("resilience.auto_resumes", 0) >= 1


def test_distributed_deterministic_failure_aborts_despite_budget(tmp_env):
    """max_restarts never retries a train_fn exception."""

    def train(hparams, reporter, ctx):
        raise ValueError("deterministic bug")

    dconf = DistributedConfig(
        hparams={}, sharding="dp", data_plane="local", hb_interval=0.05,
        max_restarts=5,
    )
    with pytest.raises(RuntimeError, match="deterministic bug"):
        experiment.lagom(train, dconf)


def test_checkpoint_restore_falls_back_to_previous_step(tmp_path):
    """Satellite: a truncated/partial latest checkpoint falls back to the
    previous retained step with a warning + checkpoint_fallback counter; an
    explicitly requested step never falls back."""
    from maggy_tpu.train.checkpoint import Checkpointer

    state1 = {"a": np.arange(8.0), "b": np.ones((2, 3))}
    state2 = {"a": np.arange(8.0) * 2, "b": np.ones((2, 3)) * 2}
    ckpt = Checkpointer(str(tmp_path / "ck"), async_save=False)
    ckpt.save(1, state1)
    ckpt.save(2, state2)
    ckpt.close()

    corrupted = chaos_mod.truncate_checkpoint(str(tmp_path / "ck"))
    assert corrupted == 2

    template = {"a": np.zeros(8), "b": np.zeros((2, 3))}
    tel = telemetry.Telemetry(worker="t", role="test")
    ckpt2 = Checkpointer(str(tmp_path / "ck"), async_save=False)
    with telemetry.current(tel):
        with pytest.warns(UserWarning, match="falling back"):
            restored = ckpt2.restore(template)
    np.testing.assert_allclose(restored["a"], state1["a"])
    assert tel.snapshot()["counters"]["checkpoint_fallback"] == 1

    # explicit step: no silent fallback
    with pytest.raises(Exception):
        ckpt2.restore(template, step=2)
    ckpt2.close()
