"""Autotuner acceptance (ISSUE 3): static AOT pruning via memory_analysis
without execution, the measured stage running through the existing HPO
driver + ASHA, a winner Trainer.fit accepts directly, and the persistent
tuning cache serving the second invocation with zero new compiles."""

import itertools
import json
import os

import jax
import numpy as np
import optax
import pytest

from maggy_tpu import telemetry
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.tune import TuneConfig, TunedConfig, cached_best, tune
from maggy_tpu.tune import static as static_mod
from maggy_tpu.tune.candidates import Candidate, enumerate_candidates

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU mesh"
)

# comfortably between the ~1-2.5 MB/device estimates of the bs=8 candidates
# and the >7 MB/device estimates of the bs=256 ones (tiny model, seq 32) —
# the bs=256 half of the grid must prune on AOT memory analysis alone
BUDGET_BYTES = 3_000_000


def _model():
    return Decoder(DecoderConfig.tiny())


def _tune_cfg(**overrides):
    base = dict(
        presets=("dp", "fsdp"),
        batch_sizes=(8, 256),
        remat_policies=(None, "nothing"),
        seq_len=32,
        hbm_budget_bytes=BUDGET_BYTES,
        measure=True,
        steps_per_unit=2,
        asha_resource_min=1,
        asha_resource_max=2,
        seed=0,
    )
    base.update(overrides)
    return TuneConfig(**base)


def _batch(batch_size, seq=32, vocab=256):
    rng = np.random.default_rng(0)
    return {"tokens": rng.integers(0, vocab, (batch_size, seq)).astype(np.int32)}


def test_tune_end_to_end_with_cache(tmp_env):
    """The acceptance scenario, one flow: >=8 candidates, >=1 AOT-pruned
    (never executed), ASHA-measured winner through the real HPO driver,
    winner accepted by Trainer.fit, second tune() served from cache with
    zero new compiles."""
    model = _model()
    cfg = _tune_cfg()
    tel = telemetry.Telemetry(worker="tune-test")
    telemetry.set_current(tel)
    try:
        result = tune(model, cfg)
    finally:
        telemetry.set_current(None)

    # ---- static stage: enumeration + AOT memory pruning, no execution
    assert result.candidates >= 8
    assert result.pruned_oom >= 1
    assert not result.cache_hit
    assert result.compiled == result.candidates  # every candidate AOT-analyzed
    oom = [r for r in result.reports if r.status == "oom"]
    ok = [r for r in result.reports if r.ok]
    assert oom and ok
    # pruning decisions came from memory_analysis numbers, not trial runs
    for r in oom:
        assert r.hbm_bytes is not None and r.hbm_bytes > BUDGET_BYTES
    for r in ok:
        assert r.hbm_bytes is not None and r.hbm_bytes <= BUDGET_BYTES
    # every oversized batch was caught statically
    assert {r.candidate.batch_size for r in oom} == {256}

    # ---- measured stage ran through the existing HPO driver with ASHA
    assert result.measured is not None
    assert result.measured["optimizer"] == "asha"
    assert result.measured["num_trials"] >= len(ok)
    assert result.measured["errors"] == 0
    # the driver persisted a real experiment record naming the controller
    exp_records = []
    for dirpath, _dirnames, filenames in os.walk(tmp_env.root):
        if "experiment.json" in filenames:
            with open(os.path.join(dirpath, "experiment.json")) as f:
                exp_records.append(json.load(f))
    assert any(rec.get("optimizer") == "Asha" for rec in exp_records)

    # pruned candidates never reached the measured stage: the winner is a
    # static-stage survivor
    assert result.best.source == "measured"
    assert result.best.batch_size in {r.candidate.batch_size for r in ok}
    assert result.best.steps_per_sec and result.best.steps_per_sec > 0

    # ---- telemetry gauges
    gauges = tel.snapshot().get("gauges", {})
    assert gauges.get("tune.candidates") == result.candidates
    assert gauges.get("tune.pruned_oom") == result.pruned_oom
    assert gauges.get("tune.best_step_time", 0) > 0

    # ---- the winner builds a trainer Trainer.fit accepts directly
    trainer = result.best.trainer(model, optax.adamw(1e-3))
    data = itertools.cycle([_batch(result.best.batch_size)])
    state = trainer.make_state(jax.random.key(0), next(data))
    state, metrics = trainer.fit(state, data, num_steps=2)
    assert np.isfinite(metrics["loss"])

    # ---- second invocation: served from the persistent cache, no compiles
    compiles_before = static_mod.COMPILE_COUNT
    result2 = tune(model, cfg)
    assert result2.cache_hit
    assert static_mod.COMPILE_COUNT == compiles_before  # zero new compiles
    assert result2.compiled == 0
    assert result2.best.to_dict() == result.best.to_dict()
    assert result2.candidates == result.candidates
    assert result2.pruned_oom == result.pruned_oom

    # grid-independent alias: consumers that never tuned (serve --mesh auto)
    # find the same winner
    alias = cached_best(model)
    assert alias is not None
    assert alias.to_dict() == result.best.to_dict()


def test_enumerate_candidates_drops_infeasible():
    """Cheap validity checks happen before any compile: indivisible batches
    vanish, microbatch options only apply to pp meshes, pp x sp never
    enumerates."""
    cfg = TuneConfig(
        presets=("dp", "fsdp", ShardingSpec(pp=2, sp=2, dp=2)),
        batch_sizes=(8, 12),  # 12 % 8 != 0 -> dropped on 8-device dp/fsdp
        microbatches=(2, 4),
        seq_len=16,
    )
    cands = enumerate_candidates(cfg, 8)
    assert cands, "dp/fsdp bs=8 candidates must survive"
    assert all(c.batch_size == 8 for c in cands)
    # non-pp meshes collapse the microbatch axis to None (no duplicates)
    assert all(c.n_microbatches is None for c in cands)
    # the pp x sp spec is invalid by construction and never enumerated
    assert all(
        not (isinstance(c.preset, ShardingSpec) and c.preset.sp > 1)
        for c in cands
    )


def test_static_report_marks_infeasible_without_raising():
    """A candidate the Trainer cannot even build reports 'infeasible'
    instead of sinking the whole tune run."""
    model = _model()
    report = static_mod.analyze_candidate(
        model,
        Candidate(preset="fsdp", batch_size=6),  # 6 rows unshardable 8-way
        _batch(6, seq=16),
        optimizer=optax.adamw(1e-3),
        budget_bytes=None,
    )
    assert report.status in ("infeasible", "ok")
    if report.status == "infeasible":
        assert report.reason


def test_tuned_config_roundtrip_and_trainer_kwargs():
    tuned = TunedConfig(
        spec=ShardingSpec(fsdp=8),
        batch_size=16,
        n_microbatches=None,
        remat_policy="nothing",
        source="measured",
        steps_per_sec=12.5,
        step_time_ms=80.0,
    )
    back = TunedConfig.from_dict(json.loads(json.dumps(tuned.to_dict())))
    assert back == tuned
    trainer = tuned.trainer(_model(), optax.adamw(1e-3))
    # remat policy applied onto the model's config
    assert trainer.model.cfg.remat and trainer.model.cfg.remat_policy == "nothing"
    assert dict(trainer.mesh.shape)["fsdp"] == 8


def test_monitor_renders_tune_gauges():
    """The dashboard's telemetry panel shows autotune progress."""
    from maggy_tpu.monitor import _telemetry_lines

    status = {
        "telemetry": {
            "0": {
                "gauges": {
                    "tune.candidates": 8.0,
                    "tune.pruned_oom": 4.0,
                    "tune.best_step_time": 16.9,
                }
            }
        }
    }
    lines = "\n".join(_telemetry_lines(status, width=78))
    assert "tune 8 cand" in lines
    assert "oom-pruned 4" in lines
    assert "best 16.9ms/step" in lines
