"""GcsEnv exercised for real over fsspec ``memory://`` (VERDICT r3 item 8):
dump/load, directory layout, the driver-registry round-trip in both secret
modes, remote sharded-dataset streaming through the env seam, and a full
lagom experiment writing every artifact into the object store."""

import importlib
import uuid

import numpy as np
import pytest

fsspec = pytest.importorskip("fsspec")

from maggy_tpu.core.env.gcs import GcsEnv


def _env():
    # unique root per test: the fsspec memory filesystem is process-global
    return GcsEnv(f"memory://maggy-{uuid.uuid4().hex[:8]}")


def test_dump_load_roundtrip_and_layout():
    env = _env()
    assert env.protocol == "memory"
    d = env.experiment_dir("app_1", 0)
    assert env.exists(d)
    t = env.trial_dir("app_1", 0, "trial_a")
    assert t.endswith("app_1/0/trial_a")

    env.dump({"metric": 0.5, "name": "x"}, f"{t}/result.json")
    assert env.load_json(f"{t}/result.json") == {"metric": 0.5, "name": "x"}
    env.dump("plain text", f"{t}/log.txt")
    with env.open_file(f"{t}/log.txt") as f:
        assert f.read() == "plain text"

    assert sorted(env.listdir(t)) == ["log.txt", "result.json"]
    with pytest.raises(OSError):
        env.listdir(f"{env.root}/nope")
    env.delete(f"{t}/log.txt")
    assert not env.exists(f"{t}/log.txt")


@pytest.mark.parametrize("omit_secret", [False, True])
def test_driver_registry_roundtrip(omit_secret):
    env = _env()
    env.register_driver(
        "app_reg", 3, "worker-host", 4242,
        secret=None if omit_secret else "s3cret", scope="pod",
    )
    rec = env.lookup_driver("app_reg")
    assert rec["host"] == "worker-host" and rec["port"] == 4242
    assert rec["scope"] == "pod" and rec["run_id"] == 3
    assert ("secret" in rec) == (not omit_secret)
    if not omit_secret:
        assert rec["secret"] == "s3cret"

    assert env.list_drivers()[0]["app_id"] == "app_reg"
    env.unregister_driver("app_reg")
    assert env.lookup_driver("app_reg") is None
    assert env.list_drivers() == []


def test_remote_sharded_dataset_streams_through_env(tmp_path):
    """ShardedDataset reads non-local shards through the ambient env's
    open_file/listdir — the GCS streaming path, on memory://."""
    from maggy_tpu.core import env as env_mod
    from maggy_tpu.train.sharded_dataset import ShardedDataset

    env = _env()
    env_mod.set_instance(env)
    try:
        data = np.arange(64 * 4, dtype=np.int32).reshape(64, 4)
        root = f"{env.root}/ds/tokens"
        bounds = np.linspace(0, 64, 5, dtype=np.int64)
        for s in range(4):
            import io

            buf = io.BytesIO()
            np.save(buf, data[bounds[s]:bounds[s + 1]])
            with env.open_file(f"{root}/shard-{s:05d}.npy", "wb") as f:
                f.write(buf.getvalue())

        ds = ShardedDataset(f"{env.root}/ds")
        assert ds.num_shards == 4 and ds.fields == ["tokens"]
        rows = [r for s in range(4) for r in np.asarray(ds.open_shard("tokens", s)).tolist()]
        assert sorted(map(tuple, rows)) == sorted(map(tuple, data.tolist()))

        loader = ds.loader(batch_size=16, loop=False, shuffle=True)
        batches = list(loader)
        assert len(batches) == 4 and all(b["tokens"].shape == (16, 4) for b in batches)
    finally:
        env_mod.set_instance(None)


def test_checkpoint_save_restore_with_remote_env(tmp_path):
    """Checkpointer under an ambient GcsEnv: orbax speaks gs:// natively via
    tensorstore (not through the env seam), so the env must not interfere
    with checkpoint save/restore — exercised with the memory:// env ambient
    and a real orbax round-trip."""
    import jax
    import optax

    from maggy_tpu.core import env as env_mod
    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.train import TrainContext
    from maggy_tpu.train.checkpoint import Checkpointer

    env_mod.set_instance(_env())
    try:
        cfg = DecoderConfig.tiny()
        ctx = TrainContext.create("dp")
        trainer = ctx.trainer(Decoder(cfg), optax.sgd(1e-2))
        batch = {"tokens": np.zeros((2, 16), np.int32)}
        state = trainer.make_state(jax.random.key(0), batch)
        ckpt = Checkpointer(str(tmp_path / "ckpt"), async_save=False)
        ckpt.save(0, state)
        ckpt.wait()
        restored = ckpt.restore(state)
        a = jax.tree.leaves(state.params)[0]
        b = jax.tree.leaves(restored.params)[0]
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        env_mod.set_instance(None)


def test_lagom_experiment_on_memory_env():
    """Full HPO run with GcsEnv ambient: experiment/trial dirs, hparams,
    result.json, executor logs and the registry record all land in the
    object store (the reference Hopsworks-env seam, hopsworks.py:136-190)."""
    experiment = importlib.import_module("maggy_tpu.experiment")
    from maggy_tpu import Searchspace
    from maggy_tpu.config import HyperparameterOptConfig
    from maggy_tpu.core import env as env_mod

    env = _env()
    env_mod.set_instance(env)
    try:
        def train(hparams, reporter):
            reporter.log(f"training with x={hparams['x']:.3f}")
            reporter.broadcast(hparams["x"], step=0)
            return hparams["x"]

        result = experiment.lagom(train, HyperparameterOptConfig(
            num_trials=3, optimizer="randomsearch",
            searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
            direction="max", num_executors=2, es_policy="none",
            hb_interval=0.05, seed=0,
        ))
        assert result["num_trials"] == 3
        app_dirs = env.listdir(env.root)
        app_id = next(a for a in app_dirs if a != ".drivers")
        run_id = sorted(env.listdir(f"{env.root}/{app_id}"))[0]
        exp = f"{env.root}/{app_id}/{run_id}"
        names = env.listdir(exp)
        assert "result.json" in names
        # executor logs publish at close through the env seam (no appends)
        assert any(n.startswith("executor_") and n.endswith(".log") for n in names)
        persisted = env.load_json(f"{exp}/result.json")
        assert persisted["best"]["metric"] == pytest.approx(result["best"]["metric"])
        # per-trial artifacts, INCLUDING the persist_outputs seam (a local
        # os.makedirs here would create a literal 'memory:/' dir in cwd)
        trial_dir = f"{exp}/{result['best']['trial_id']}"
        trial_names = env.listdir(trial_dir)
        assert "trial.json" in trial_names
        assert ".outputs.json" in trial_names
        import os as _os

        assert not _os.path.exists("memory:"), "artifacts leaked to local cwd"
    finally:
        env_mod.set_instance(None)
