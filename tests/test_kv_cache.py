"""KV-cache incremental decoding: numerical equivalence with the full forward
pass, cached vs recompute generation agreement, and cache shapes through the
scanned layer stack."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate, generate_cached, init_cache


@pytest.fixture(scope="module")
def setup():
    cfg = DecoderConfig.tiny(max_seq_len=32)
    model = Decoder(cfg)
    tokens = jnp.asarray(np.arange(16)[None, :] % cfg.vocab_size, dtype=jnp.int32)
    # param seed deliberately != the key(0) init_cache uses internally — a
    # cache polluted by init-time params must not be coincidentally correct
    variables = model.init(jax.random.key(7), tokens)
    decode_model = Decoder(dataclasses.replace(cfg, decode=True))
    return cfg, model, decode_model, variables, tokens


@pytest.mark.slow
def test_incremental_matches_full_forward(setup):
    cfg, model, decode_model, variables, tokens = setup
    full = np.asarray(model.apply(variables, tokens))
    cache = init_cache(decode_model, tokens)
    outs = []
    for p in range(tokens.shape[1]):
        logits, mut = decode_model.apply(
            {"params": variables["params"], "cache": cache},
            tokens[:, p : p + 1],
            jnp.full((1, 1), p, jnp.int32),
            mutable=["cache"],
        )
        cache = mut["cache"]
        outs.append(np.asarray(logits[:, 0]))
    inc = np.stack(outs, axis=1)
    np.testing.assert_allclose(inc, full, atol=2e-2)  # bf16 accumulation noise


def test_multi_chunk_cache_reads_match_full_forward():
    """Length-adaptive chunked cache reads (decode_chunk < max_seq_len): the
    cross-chunk online-softmax recurrence must reproduce the full forward —
    geometry chosen so 4 chunks are live and the prefix crosses chunk
    boundaries mid-decode (VERDICT r3 item 7 path, multi-chunk case)."""
    cfg = DecoderConfig.tiny(max_seq_len=64, decode_chunk=16, dtype=jnp.float32)
    model = Decoder(cfg)
    tokens = jnp.asarray(
        np.arange(56)[None, :] % cfg.vocab_size, dtype=jnp.int32
    )
    variables = model.init(jax.random.key(3), tokens)
    decode_model = Decoder(dataclasses.replace(cfg, decode=True))
    full = np.asarray(model.apply(variables, tokens))
    cache = init_cache(decode_model, tokens)
    outs = []
    for p in range(tokens.shape[1]):
        logits, mut = decode_model.apply(
            {"params": variables["params"], "cache": cache},
            tokens[:, p : p + 1],
            jnp.full((1, 1), p, jnp.int32),
            mutable=["cache"],
        )
        cache = mut["cache"]
        outs.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(outs, axis=1), full, atol=2e-4)


def test_cache_shapes_scanned(setup):
    cfg, _, decode_model, _, tokens = setup
    cache = init_cache(decode_model, tokens)
    k = cache["layers"]["layer"]["attn"]["k"]
    # [n_layers, B, max_seq_len, kv_heads, head_dim] — layer axis from nn.scan
    assert k.shape == (cfg.n_layers, 1, cfg.max_seq_len, cfg.n_kv_heads, cfg.head_dim)


def test_cached_generation_matches_recompute(setup):
    cfg, model, decode_model, variables, _ = setup
    prompt = np.zeros((2, 24), dtype=np.int32)
    prompt[0, :5] = [3, 6, 9, 12, 15]
    prompt[1, :7] = np.arange(7) * 2
    plen = jnp.asarray([5, 7])
    a = np.asarray(generate(model, variables, jnp.asarray(prompt), plen))
    b = np.asarray(
        generate_cached(decode_model, variables["params"], jnp.asarray(prompt), plen)
    )
    assert (a == b).mean() > 0.95  # bf16 ties may break differently


@pytest.mark.slow
def test_moe_decoder_cached_generation():
    """The MoE decoder shares the Attention module, so KV-cache decode works
    for it too. (Note: per-step routing never drops tokens — capacity >=
    top_k at t=1 — so under congestion decode can be *more* faithful than the
    capacity-limited training forward; uncongested they agree.)"""
    from maggy_tpu.models import MoEConfig, MoEDecoder

    cfg = MoEConfig.tiny_moe(max_seq_len=24)
    model = MoEDecoder(cfg)
    tokens = jnp.asarray(np.arange(12)[None, :] % cfg.vocab_size, dtype=jnp.int32)
    variables = model.init(jax.random.key(3), tokens)
    full = np.asarray(model.apply(variables, tokens))

    decode_model = MoEDecoder(dataclasses.replace(cfg, decode=True))
    cache = init_cache(decode_model, tokens)
    outs = []
    for p in range(12):
        logits, mut = decode_model.apply(
            {"params": variables["params"], "cache": cache},
            tokens[:, p : p + 1],
            jnp.full((1, 1), p, jnp.int32),
            mutable=["cache"],
        )
        cache = mut["cache"]
        outs.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(outs, 1), full, atol=3e-2)

    prompt = np.zeros((1, 16), dtype=np.int32)
    prompt[0, :4] = [1, 2, 3, 4]
    a = np.asarray(generate(model, variables, jnp.asarray(prompt), jnp.asarray([4])))
    b = np.asarray(
        generate_cached(
            decode_model, variables["params"], jnp.asarray(prompt), jnp.asarray([4])
        )
    )
    assert (a == b).mean() > 0.9


def test_cached_generation_eos(setup):
    cfg, model, decode_model, variables, _ = setup
    prompt = np.zeros((1, 16), dtype=np.int32)
    prompt[0, :4] = [1, 2, 3, 4]
    plen = jnp.asarray([4])
    free = np.asarray(
        generate_cached(decode_model, variables["params"], jnp.asarray(prompt), plen)
    )
    eos = int(free[0, 4])
    out = np.asarray(
        generate_cached(
            decode_model, variables["params"], jnp.asarray(prompt), plen, eos_id=eos
        )
    )
    hits = np.where(out[0] == eos)[0]
    assert hits.size and (out[0, hits[0]:] == eos).all()


@pytest.mark.slow
def test_tp_decode_cache_sharded():
    """On a tp mesh the KV cache shards its kv-head dim over tensor (1/tp per
    device, not a full replica) and cached generation still matches the
    recompute path."""
    from maggy_tpu.models.generate import cache_shardings
    from maggy_tpu.parallel.mesh import make_mesh
    from maggy_tpu.parallel.spec import AXIS_TENSOR, ShardingSpec

    cfg = DecoderConfig.tiny(max_seq_len=32)  # 2 kv heads
    mesh = make_mesh(ShardingSpec(tp=2), jax.devices()[:2])
    model = Decoder(cfg)
    tokens = jnp.asarray(np.arange(16)[None, :] % cfg.vocab_size, dtype=jnp.int32)
    variables = model.init(jax.random.key(7), tokens)
    decode_model = Decoder(dataclasses.replace(cfg, decode=True))

    cache = init_cache(decode_model, tokens, mesh=mesh)
    k = cache["layers"]["layer"]["attn"]["k"]
    spec = k.sharding.spec
    assert spec[-2] == AXIS_TENSOR, spec  # kv heads sharded, cache not replicated
    shard_shape = k.sharding.shard_shape(k.shape)
    assert shard_shape[-2] == cfg.n_kv_heads // 2

    # numerics: incremental decode on the sharded cache == full forward
    full = np.asarray(model.apply(variables, tokens))
    outs = []
    with mesh:
        for p in range(tokens.shape[1]):
            logits, mut = decode_model.apply(
                {"params": variables["params"], "cache": cache},
                tokens[:, p : p + 1],
                jnp.full((1, 1), p, jnp.int32),
                mutable=["cache"],
            )
            cache = mut["cache"]
            outs.append(np.asarray(logits[:, 0]))
    inc = np.stack(outs, axis=1)
    np.testing.assert_allclose(inc, full, atol=2e-2)


@pytest.mark.slow
def test_packed_prefill_logits_match_per_sequence(setup):
    """VERDICT r4 item 4: a packed prompt batch prefills in ONE pass, and the
    segment mask isolates each segment — every segment's prefill logits
    equal a plain forward over that sequence alone."""
    from maggy_tpu.models.generate import prefill

    cfg, model, decode_model, variables, _ = setup
    rng = np.random.default_rng(3)
    s1 = rng.integers(1, cfg.vocab_size, 6).astype(np.int32)
    s2 = rng.integers(1, cfg.vocab_size, 10).astype(np.int32)
    packed = jnp.asarray(np.concatenate([s1, s2])[None])  # [1, 16]
    positions = jnp.asarray(
        np.concatenate([np.arange(6), np.arange(10)])[None].astype(np.int32)
    )
    seg = jnp.asarray(np.concatenate([np.zeros(6), np.ones(10)])[None].astype(np.int32))

    logits, cache = prefill(
        decode_model, variables["params"], packed, positions, seg
    )
    # every scanned layer's write index advanced by the full prompt length
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        if "index" in jax.tree_util.keystr(path):
            assert all(int(v) == 16 for v in np.asarray(leaf).ravel())
    ref1 = np.asarray(model.apply(variables, jnp.asarray(s1[None])))
    ref2 = np.asarray(model.apply(variables, jnp.asarray(s2[None])))
    got = np.asarray(logits)
    np.testing.assert_allclose(got[:, :6], ref1, atol=3e-2)
    np.testing.assert_allclose(got[:, 6:], ref2, atol=3e-2)


@pytest.mark.slow
def test_packed_prefill_decode_matches_unpacked_decode(setup):
    """Packed prefill + cached decode of each row's LAST segment equals the
    per-sequence unpacked cached decode — greedy tokens must match exactly."""
    from maggy_tpu.models.generate import generate_cached_packed

    cfg, model, decode_model, variables, _ = setup
    rng = np.random.default_rng(4)
    MAX_NEW = 6
    rows = []
    poss = []
    segs = []
    lasts = []
    for r in range(2):
        a = rng.integers(1, cfg.vocab_size, 5).astype(np.int32)
        b = rng.integers(1, cfg.vocab_size, 7).astype(np.int32)
        rows.append(np.concatenate([a, b]))
        poss.append(np.concatenate([np.arange(5), np.arange(7)]))
        segs.append(np.concatenate([np.zeros(5), np.ones(7)]))
        lasts.append(b)
    packed = jnp.asarray(np.stack(rows).astype(np.int32))
    positions = jnp.asarray(np.stack(poss).astype(np.int32))
    seg = jnp.asarray(np.stack(segs).astype(np.int32))

    _, new_tokens = generate_cached_packed(
        decode_model, variables["params"], packed, positions, seg,
        max_new=MAX_NEW,
    )

    # unpacked reference: each last segment decoded alone through the
    # existing cached path
    for r, b in enumerate(lasts):
        buf = np.zeros((1, 7 + MAX_NEW), np.int32)
        buf[0, :7] = b
        ref = generate_cached(
            decode_model, variables["params"], jnp.asarray(buf),
            jnp.asarray([7], jnp.int32),
        )
        np.testing.assert_array_equal(
            np.asarray(new_tokens)[r], np.asarray(ref)[0, 7:],
            err_msg=f"row {r}: packed continuation diverges from unpacked",
        )


@pytest.mark.slow
def test_packed_prefill_cache_overflow_raises(setup):
    from maggy_tpu.models.generate import generate_cached_packed

    cfg, model, decode_model, variables, _ = setup
    packed = jnp.zeros((1, 30), jnp.int32)
    positions = jnp.zeros((1, 30), jnp.int32)
    seg = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate_cached_packed(
            decode_model, variables["params"], packed, positions, seg,
            max_new=8,
        )
