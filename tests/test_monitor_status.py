"""STATUS verb + monitor dashboard: structured experiment snapshots for
monitors (the reference only ships log lines via sparkmagic LOG polling)."""

import threading
import time

import pytest

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.core import rpc
from maggy_tpu.monitor import render_status


def test_status_verb_live_hpo(tmp_env):
    """Attach a client mid-run and read a structured STATUS snapshot."""
    release = threading.Event()
    statuses = []

    def train(hparams, reporter):
        release.wait(timeout=30)
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=4, optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
        direction="max", num_executors=2, hb_interval=0.05, name="status-e2e",
    )
    holder = {}
    t = threading.Thread(
        target=lambda: holder.update(r=experiment.lagom(train, cfg))
    )
    t.start()
    deadline = time.time() + 30
    driver = None
    while time.time() < deadline:
        driver = experiment.CURRENT_DRIVER
        if driver is not None and driver.server is not None and driver.server.port:
            break
        time.sleep(0.05)
    assert driver is not None

    client = rpc.Client(
        ("127.0.0.1", driver.server.port), partition_id=-1,
        secret=driver.server.secret,
    )
    try:
        # first trial assignment happens on the digestion thread after worker
        # REG — poll until the controller has recorded a decision
        deadline = time.time() + 30
        while time.time() < deadline:
            status = client._request({"type": "STATUS"})
            if status.get("controller_log"):
                break
            time.sleep(0.05)
        statuses.append(status)
    finally:
        client.stop()
        release.set()
        t.join(timeout=60)

    s = statuses[0]
    assert s["kind"] == "HyperparameterOptDriver"
    assert s["state"] == "RUNNING"
    assert s["trials_total"] == 4
    assert s["controller"] == "RandomSearch"
    assert s["num_executors"] == 2
    # decisions were recorded for the in-flight assignments
    assert any("trial" in line for line in s["controller_log"])
    assert holder["r"]["num_trials"] == 4


def test_render_status_hpo_panel():
    out = render_status(
        {
            "kind": "HyperparameterOptDriver",
            "name": "exp",
            "state": "RUNNING",
            "app_id": "app_1",
            "run_id": 1,
            "elapsed_s": 12.5,
            "direction": "max",
            "controller": "asha",
            "trials_done": 3,
            "trials_total": 8,
            "trials_running": 2,
            "early_stopped": 1,
            "errors": 0,
            "best": {
                "trial_id": "abcd", "metric": 0.91234,
                "params": {"lr": 0.0031, "opt": "adam"},
            },
            "controller_log": ["[12:00:00] random trial abcd -> executor 0"],
        }
    )
    assert "exp [HyperparameterOptDriver] state=RUNNING" in out
    assert "3/8" in out
    assert "best max 0.91234" in out and "lr=0.0031" in out
    assert "asha decisions" in out
    assert "executor 0" in out


def test_render_status_distributed_panel():
    out = render_status(
        {
            "kind": "DistributedTrainingDriver",
            "name": "dist",
            "state": "RUNNING",
            "app_id": "a",
            "run_id": 2,
            "elapsed_s": 3.0,
            "num_executors": 3,
            "workers_done": 1,
            "evaluator_partition": 2,
            "last_seen": {"0": 0.2, "1": 0.1, "2": 5.0},
        }
    )
    assert "workers 1/3 done" in out
    assert "evaluator=partition 2" in out
    assert "w2:5.0s" in out
