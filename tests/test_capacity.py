"""Capacity observability (ISSUE 16): the HBM memory ledger's CPU-sim
reconciliation contract, KV page heat / fragmentation / eviction ordering,
prefix residency, the fleet capacity view reproduced offline through
``tools/metrics_query.py --merge``, alert-triggered profile capture, the
trace-attribution v2 back-compat guarantee, and the capacity-rule lint."""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from maggy_tpu.serve.paging.allocator import BlockAllocator
from maggy_tpu.serve.prefix import PrefixIndex
from maggy_tpu.telemetry import memtrack
from maggy_tpu.telemetry.alerts import ALERT_FIRING, AlertEvaluator
from maggy_tpu.telemetry.histogram import LatencyHistogram
from maggy_tpu.telemetry.memtrack import MemoryLedger, array_bytes
from maggy_tpu.telemetry.profcap import ProfileCapture
from maggy_tpu.telemetry.recorder import Telemetry
from maggy_tpu.telemetry.timeseries import SeriesStore


def load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- allocator heat & eviction


def test_heat_buckets_and_coldest_eviction_ordering():
    a = BlockAllocator(num_pages=17, page_size=4)
    pages = a.alloc(8)
    a.touch(pages[:4], gen=100)  # hot at gen 104 (age 4)
    a.touch(pages[4:6], gen=40)  # warm at gen 104 (age 64, boundary)
    # pages[6:8] never touched -> cold
    heat = a.heat_buckets(104)
    assert heat == {"hot": 4, "warm": 2, "cold": 2}
    # eviction ordering: never-touched pages first, then oldest stamps —
    # the known-cold pages are selected before anything recently read
    cold = a.coldest()
    assert cold[:2] == sorted(pages[6:8])
    assert set(cold[2:4]) == set(pages[4:6])
    assert set(cold[4:]) == set(pages[:4])
    assert a.coldest(3) == cold[:3]
    # touching a freed page is ignored (stale caller lists race release)
    a.release(pages[:1])
    a.touch(pages[:1], gen=200)
    a.check_invariants()
    assert pages[0] not in a.coldest()


def test_fragmentation_empty_full_and_fragmented_pools():
    a = BlockAllocator(num_pages=9, page_size=4)
    # all-free pool: one contiguous run, no fragmentation
    f = a.fragmentation()
    assert (f["free_runs"], f["largest_run"], f["frag_ratio"]) == (1, 8, 0.0)
    assert f["pages_pinned_shared"] == 0 and f["pages_reclaimable"] == 0
    # full pool: nothing free, ratio pinned at 0 (nothing to fragment)
    pages = a.alloc(8)
    f = a.fragmentation()
    assert (f["free_runs"], f["largest_run"], f["frag_ratio"]) == (0, 0, 0.0)
    assert f["pages_reclaimable"] == 8
    # checkerboard release: every free page is its own run
    a.release(pages[::2])
    f = a.fragmentation()
    assert f["free_runs"] == 4 and f["largest_run"] == 1
    assert f["frag_ratio"] == pytest.approx(0.75)
    a.check_invariants()
    # releasing the rest re-coalesces into one run
    a.release(pages[1::2])
    assert a.fragmentation()["frag_ratio"] == 0.0
    a.check_invariants()


# ------------------------------------------------------------- memory ledger


def test_ledger_sim_reconciliation_within_10pct(monkeypatch):
    monkeypatch.setattr(memtrack, "device_memory", lambda: None)
    ledger = MemoryLedger()
    ledger.register("params", 512 << 20)
    ledger.register("optimizer", 1 << 30)
    ledger.register("kv_pages", 256 << 20)
    ledger.register("prefetch", 32 << 20)
    rec = ledger.reconcile()
    assert rec["source"] == "sim"
    # the reconciliation contract: account sum within 10% of reported-used,
    # the gap surfaced as unattributed — never an error
    assert abs(rec["hbm_used"] - rec["accounted"]) <= 0.10 * rec["hbm_used"]
    assert rec["unattributed"] == rec["hbm_used"] - rec["accounted"]
    assert rec["hbm_used"] + rec["hbm_free"] == rec["hbm_limit"]
    assert rec["accounts"]["optimizer"] == 1 << 30
    # idempotent re-register replaces (reconfigure never double-counts)
    ledger.register("kv_pages", 128 << 20)
    assert ledger.accounts()["kv_pages"] == 128 << 20
    ledger.unregister("prefetch")
    assert "prefetch" not in ledger.accounts()


def test_ledger_tick_exports_and_headroom_counters(monkeypatch):
    monkeypatch.setattr(memtrack, "device_memory", lambda: None)
    ledger = MemoryLedger()
    ledger.register("kv_pages", 1000)
    store = SeriesStore()
    tel = Telemetry(worker="ledger-test")
    rec = ledger.tick(store=store, telemetry=tel, now=100.0)
    assert rec["headroom_ok"] == 1 and rec["headroom_miss"] == 0
    # shrink the sim pool: headroom collapses under the 10% low-water mark
    ledger.sim_limit_bytes = 1100
    rec = ledger.tick(store=store, telemetry=tel, now=101.0)
    assert rec["headroom_miss"] == 1 and rec["headroom_pct"] < 0.10
    # gauges + per-account series + the burn-rule counter pair all landed
    assert store.get("mem.headroom_pct").latest()[1] == rec["headroom_pct"]
    assert store.get("mem.account.kv_pages").latest()[1] == 1000.0
    assert store.get("mem.unattributed").latest()[1] == float(rec["unattributed"])
    assert store.get("mem.headroom_ok").kind == "counter"
    assert store.get("mem.headroom_miss").latest()[1] == 1
    snap = ledger.snapshot()
    assert snap["headroom_ok"] == 1 and snap["headroom_miss"] == 1


def test_ledger_tick_never_raises(monkeypatch):
    ledger = MemoryLedger()
    ledger.register("params", 100)

    class _BoomStore:
        def ingest(self, *a, **k):
            raise RuntimeError("boom")

    # a broken export sink is swallowed; the reconcile still returns
    rec = ledger.tick(store=_BoomStore(), telemetry=None, now=1.0)
    assert rec["accounted"] == 100
    # a broken device probe inside reconcile degrades to {} — never a raise
    def _boom():
        raise RuntimeError("probe died")

    monkeypatch.setattr(memtrack, "device_memory", _boom)
    assert ledger.tick(store=None, telemetry=None, now=2.0) == {}


def test_array_bytes_walks_plain_trees():
    tree = {
        "a": np.zeros((4, 8), np.float32),
        "b": [np.zeros(16, np.int32), (np.zeros(2, np.float64),)],
        "c": "not-an-array",
    }
    assert array_bytes(tree) == 4 * 8 * 4 + 16 * 4 + 2 * 8
    assert array_bytes(None) == 0


# ----------------------------------------------------------- prefix residency


def test_prefix_residency_stats_rank_by_hits():
    idx = PrefixIndex()
    idx.bytes_per_token = 100
    p1 = list(range(1, 17))
    p2 = list(range(40, 52))
    idx.insert(0, p1, gen=0)
    idx.insert(1, p2, gen=2)
    for g in (5, 6, 7):
        assert idx.match(p1, gen=g) is not None
    res = idx.residency_stats(gen=10, top=4)
    assert res["resident_prefixes"] == 2
    assert res["resident_tokens"] == len(p1) + len(p2)
    assert res["resident_bytes"] == (len(p1) + len(p2)) * 100
    top = res["top"]
    assert top[0]["slot"] == 0 and top[0]["hits"] == 3
    assert top[0]["bytes"] == len(p1) * 100
    # digests are content-stable: same tokens, same digest, cross-process
    assert top[0]["digest"] == PrefixIndex.digest(tuple(p1))
    assert len(top[0]["digest"]) == 8


# --------------------------------------------------- alert-triggered profcap


def test_profcap_fires_once_on_injected_pressure(tmp_path, monkeypatch):
    """Acceptance: injected HBM pressure drives the real burn rule; the
    controller arms exactly ONE bounded capture whose dump carries the
    alerted series tails."""
    monkeypatch.delenv("MAGGY_TPU_PROFCAP", raising=False)
    monkeypatch.setattr(memtrack, "device_memory", lambda: None)
    store = SeriesStore()
    tel = Telemetry(worker="profcap-pressure-test")
    ledger = MemoryLedger()
    ledger.register("params", 900 << 20)
    ledger.sim_limit_bytes = 1 << 30  # ~7.7% headroom: every tick a miss
    ev = AlertEvaluator(store, tel, scope="worker")
    pc = ProfileCapture(dump_dir=str(tmp_path))
    t0 = 50_000.0
    fired = []
    for tick in range(60):
        now = t0 + tick
        ledger.tick(store=store, telemetry=tel, now=now)
        path = pc.tick(ev.evaluate(now), now=now)
        if path:
            fired.append(path)
    assert len(fired) == 1  # fires once; the still-firing alert never re-arms
    with open(os.path.join(fired[0], "capture.json"), encoding="utf-8") as f:
        payload = json.load(f)
    assert payload["reason"] == "alert:alert.hbm_headroom"
    assert payload["trigger"]["alert"] == "alert.hbm_headroom"
    assert payload["profiler"] in ("fallback", "jax.profiler")
    assert any(a["alert"] == "alert.hbm_headroom" for a in payload["alerts"])
    # the dump is self-describing: tails of the series that tripped the rule
    assert any("mem.headroom_miss" in k for k in payload["alert_series"])
    assert payload["threads"]
    snap = pc.snapshot()
    assert snap["captures"] == 1 and snap["paths"] == fired


def test_profcap_cooldown_and_capture_cap(tmp_path, monkeypatch):
    monkeypatch.delenv("MAGGY_TPU_PROFCAP", raising=False)
    trans = [{"event": ALERT_FIRING, "alert": "alert.fragmentation"}]
    pc = ProfileCapture(dump_dir=str(tmp_path), cooldown_s=100.0, max_captures=2)
    assert pc.tick(trans, now=1000.0) is not None
    assert pc.tick(trans, now=1050.0) is None  # inside cooldown
    assert pc.tick(trans, now=1200.0) is not None  # cooldown elapsed
    assert pc.tick(trans, now=2000.0) is None  # over the per-process cap
    assert pc.snapshot()["captures"] == 2
    # unwatched alerts and resolve transitions never arm
    assert pc.tick([{"event": ALERT_FIRING, "alert": "alert.queue_depth_high"}],
                   now=3000.0) is None
    assert pc.tick([{"event": "alert.resolved", "alert": "alert.fragmentation"}],
                   now=4000.0) is None


def test_profcap_env_flag_disables(tmp_path, monkeypatch):
    monkeypatch.setenv("MAGGY_TPU_PROFCAP", "0")
    pc = ProfileCapture(dump_dir=str(tmp_path))
    trans = [{"event": ALERT_FIRING, "alert": "alert.hbm_headroom"}]
    assert pc.tick(trans, now=1.0) is None
    snap = pc.snapshot()
    assert snap["captures"] == 0 and snap["enabled"] is False
    assert os.listdir(str(tmp_path)) == []


# -------------------------------------------- fleet capacity view & offline


def _capacity_replica_stats(h, resid_bytes, resid_count, headroom, heat,
                            frag_ratio, done):
    return {
        "num_slots": 4, "active_slots": 2, "queue_depth": 1,
        "tokens_per_sec": 120.0, "requests_done": done,
        "ttft_ms_p50": h.percentile(0.5), "ttft_ms_p95": h.percentile(0.95),
        "latency": {"ttft_ms": h.to_dict()},
        "slo_ok": 10, "slo_miss": 0,
        "paging": {
            "paged": True, "pages_total": 32, "pages_free": 10,
            "pages_shared": 0,
            "heat": dict(heat),
            "fragmentation": {
                "free_runs": 2, "largest_run": 5, "frag_ratio": frag_ratio,
            },
        },
        "memory": {"headroom_pct": headroom},
        "prefix_residency": {
            "resident_prefixes": resid_count,
            "resident_tokens": resid_bytes // 100,
            "resident_bytes": resid_bytes,
            "top": [{
                "digest": "abcd1234", "slot": 0,
                "tokens": resid_bytes // 100, "bytes": resid_bytes, "hits": 3,
            }],
        },
    }


def test_fleet_capacity_view_and_offline_merge(tmp_path, capsys):
    """Acceptance: a 2-replica fleet's residency/headroom view is reproduced
    EXACTLY from per-replica METRICS exports via metrics_query --merge."""
    from maggy_tpu.serve.fleet import Router, RouterConfig
    from tests.test_serve_fleet import fake_replica

    mq = load_tool("metrics_query")
    tel = Telemetry(worker="fleet-capacity-test")
    router = Router(
        [fake_replica(0), fake_replica(1)],
        config=RouterConfig(),
        telemetry_recorder=tel,
    )
    hists = [LatencyHistogram(), LatencyHistogram()]
    resid = [4096, 6144]
    headroom = [0.42, 0.17]
    frags = [0.25, 0.6]
    t0 = 42_000.0
    for tick in range(12):
        for r in range(2):
            hists[r].observe(20.0)
            router._stats_cache[r] = _capacity_replica_stats(
                hists[r], resid[r], r + 1, headroom[r],
                {"hot": 3 + r, "warm": 2, "cold": 1}, frags[r], tick * 2,
            )
        router._sample_metrics(t0 + tick)

    # FSTATS capacity view: sums / fleet-min headroom / fleet-max frag
    cap = router._fleet_stats()["capacity"]
    assert cap["resident_bytes"] == sum(resid)
    assert cap["resident_prefixes"] == 3
    assert cap["headroom_pct"] == pytest.approx(min(headroom))
    assert cap["fragmentation"] == pytest.approx(max(frags))
    assert (cap["pages_hot"], cap["pages_warm"], cap["pages_cold"]) == (7, 4, 2)
    # same digest on both replicas -> ONE anchor, bytes/hits summed
    tops = cap["top_prefixes"]
    assert len(tops) == 1
    assert tops[0]["bytes"] == sum(resid) and tops[0]["hits"] == 6
    assert sorted(tops[0]["replicas"]) == [0, 1]

    # offline reproduction from the exported per-replica stores
    body = router._metrics_body()
    paths = []
    for k in sorted(body["replicas"]):
        p = os.path.join(str(tmp_path), f"r{k}.json")
        with open(p, "w") as f:
            json.dump(body["replicas"][k], f)
        paths.append(p)
    fleet_store = SeriesStore.from_snapshot(body["metrics"])
    now = t0 + 11
    assert mq.main(["--merge", *paths, "--name", "serve.prefix_resident_bytes",
                    "--window", "30", "--now", str(now)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["kind"] == "gauge" and out["sum"] == float(sum(resid))
    assert fleet_store.get("serve.prefix_resident_bytes").latest()[1] == float(
        sum(resid)
    )
    assert mq.main(["--merge", *paths, "--name", "mem.headroom_pct",
                    "--window", "30", "--now", str(now)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["min"] == pytest.approx(min(headroom))
    assert fleet_store.get("mem.headroom_pct").latest()[1] == pytest.approx(
        min(headroom)
    )


# ------------------------------------------------- attribution v2 back-compat


def test_attribution_v2_reads_v1_jsonl(tmp_path):
    from maggy_tpu.telemetry import attribution

    tdir = os.path.join(str(tmp_path), "telemetry")
    os.makedirs(tdir)

    def ev(name, ts, trace, **attrs):
        return {"kind": "event", "name": name, "ts": ts, "worker": "serve",
                "trace": trace, "attrs": attrs}

    records = [
        # v1-era request: no capacity attrs anywhere
        ev("req.queued", 100.0, "t1", rid="r1"),
        ev("req.admitted", 100.1, "t1", rid="r1"),
        ev("req.finished", 100.5, "t1", rid="r1", state="done"),
        # v2 request: headroom stamped at admit, page peak at finish
        ev("req.queued", 200.0, "t2", rid="r2"),
        ev("req.admitted", 200.1, "t2", rid="r2", headroom_at_admit=0.33),
        ev("req.finished", 200.6, "t2", rid="r2", state="done",
           pages_held_peak=5),
    ]
    with open(os.path.join(tdir, "worker_1.jsonl"), "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")

    out = attribution.analyze(str(tmp_path))
    assert out["schema"] == "maggy-tpu.trace-attribution.v2"
    by = {r["trace"]: r for r in out["requests"]}
    # back-compat: v1 rows parse cleanly, new fields read as None
    assert by["t1"]["state"] == "done"
    assert by["t1"]["pages_held_peak"] is None
    assert by["t1"]["headroom_at_admit"] is None
    assert by["t2"]["pages_held_peak"] == 5
    assert by["t2"]["headroom_at_admit"] == 0.33


# ------------------------------------------------------- capacity-rule lint


def test_capacity_rules_lint_catches_miswiring():
    import types

    ctn = load_tool("check_telemetry_names")

    class R:
        windows = ((30.0, 2.0), (5.0, 2.0))

        def __init__(self, **kw):
            self.__dict__.update(kw)

    good_rules = (
        R(name="alert.hbm_headroom", kind="burn_rate",
          ok_metric="mem.headroom_ok", miss_metric="mem.headroom_miss"),
        R(name="alert.fragmentation", kind="threshold",
          metric="serve.fragmentation"),
    )
    assert ctn.check_capacity_rules(types.SimpleNamespace(RULES=good_rules)) == []
    # deleting a rule silently disarms profcap -> the lint names it
    missing = types.SimpleNamespace(RULES=good_rules[:1])
    assert any("alert.fragmentation" in v
               for v in ctn.check_capacity_rules(missing))
    # re-pointing the burn pair at another series is flagged field-by-field
    repointed = types.SimpleNamespace(RULES=(
        R(name="alert.hbm_headroom", kind="burn_rate",
          ok_metric="serve.slo_ok", miss_metric="mem.headroom_miss"),
        good_rules[1],
    ))
    assert any("ok_metric" in v for v in ctn.check_capacity_rules(repointed))
    # a single-window burn rule loses the fast-resolve property
    slow = R(name="alert.hbm_headroom", kind="burn_rate",
             ok_metric="mem.headroom_ok", miss_metric="mem.headroom_miss")
    slow.windows = ((30.0, 2.0),)
    one_window = types.SimpleNamespace(RULES=(slow, good_rules[1]))
    assert any("2 windows" in v for v in ctn.check_capacity_rules(one_window))
    # and the checked-in registry itself is clean
    assert ctn.check_capacity_rules(ctn.load_alerts(REPO)) == []


# -------------------------------------------------- engine capacity surfaces


def test_engine_registers_accounts_and_capacity_surfaces():
    import jax
    import jax.numpy as jnp

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.parallel.sharding import unbox
    from maggy_tpu.serve import Engine, Request, SamplingParams

    cfg = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)
    params = unbox(
        Decoder(cfg).init(jax.random.key(3), jnp.zeros((1, 8), jnp.int32))["params"]
    )
    engine = Engine(cfg, params, num_slots=2)
    acc = engine.memory.accounts()
    assert acc["params"] > 0 and acc["kv_pages"] > 0 and acc["workspace"] > 0
    assert engine.prefix_index.bytes_per_token >= 1
    rec = engine.memory.reconcile()
    if rec["source"] == "sim":  # the CPU tier-1 path
        assert rec["unattributed"] <= 0.10 * rec["hbm_used"]
    slot, _ = engine.admit(
        Request(prompt=[3, 1, 4, 1, 5, 9, 2, 6], params=SamplingParams(max_new=4))
    )
    assert engine.pages_held_peak(slot) >= 1
    ps = engine.paging_stats
    assert ps["heat"]["hot"] >= 1
    assert 0.0 <= ps["fragmentation"]["frag_ratio"] <= 1.0
    res = engine.prefix_stats["prefix_residency"]
    assert res["resident_prefixes"] == 1 and res["resident_bytes"] > 0
    engine.release(slot)
    assert engine.pages_held_peak(slot) == 0
    assert engine.prefix_stats["prefix_residency"]["resident_prefixes"] == 0
    engine.allocator.check_invariants()


# ----------------------------------------------------------- bench gate


def test_bench_capacity_gate():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.remove(REPO)
    out = bench.bench_capacity(quick=True)
    assert out["within_budget"] is True
    assert 0.0 < out["mem_headroom_pct"] <= 1.0
    assert out["accounts"] == 4
