"""MoE / ResNet / BERT model-family tests, including expert-parallel training."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from maggy_tpu.models import Bert, BertConfig, MoEConfig, MoEDecoder, ResNet, ResNetConfig
from maggy_tpu.parallel.spec import ShardingSpec
from maggy_tpu.train import TrainContext
from maggy_tpu.train.data import synthetic_lm_batches


def test_moe_forward_and_routing():
    cfg = MoEConfig.tiny_moe()
    model = MoEDecoder(cfg)
    tokens = jnp.asarray(np.arange(32)[None, :] % cfg.vocab_size, dtype=jnp.int32)
    variables = model.init(jax.random.key(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (1, 32, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # router params exist per expert
    moe_params = variables["params"]["layers"]["layer"]["moe"]
    assert moe_params["w_gate"].value.shape == (cfg.n_layers, cfg.n_experts, 64, 96)


@pytest.mark.slow
def test_moe_trains_expert_parallel():
    """MoE decoder learns under an ep x fsdp mesh (BASELINE config 5 shape)."""
    cfg = MoEConfig.tiny_moe()
    ctx = TrainContext.create(ShardingSpec(ep=4, dp=2))
    trainer = ctx.trainer(MoEDecoder(cfg), optax.adamw(3e-3))
    data = synthetic_lm_batches(cfg.vocab_size, 8, 32, seed=2)
    state = trainer.make_state(jax.random.key(0), next(data))

    import flax.linen as nn

    wg = state.params["layers"]["layer"]["moe"]["w_gate"]
    val = wg.value if isinstance(wg, nn.Partitioned) else wg
    assert "expert" in str(val.sharding.spec)

    first = last = None
    for _ in range(25):
        state, m = trainer.step(state, trainer.shard_batch(next(data)))
        if first is None:
            first = float(m["loss"])
        last = float(m["loss"])
    assert last < first * 0.9, (first, last)


def test_moe_capacity_drops_are_bounded():
    """With capacity_factor >= top_k * 1.0 and uniform-ish routing the output
    must differ from zero for nearly all tokens (tokens dropped only beyond
    capacity)."""
    cfg = MoEConfig.tiny_moe(capacity_factor=2.0)
    model = MoEDecoder(cfg)
    tokens = jnp.asarray(np.arange(64)[None, :] % cfg.vocab_size, dtype=jnp.int32)
    variables = model.init(jax.random.key(1), tokens)
    logits = model.apply(variables, tokens)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow
def test_resnet_forward():
    cfg = ResNetConfig.resnet18(num_classes=10)
    model = ResNet(cfg)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x)
    out = model.apply(variables, x)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


@pytest.mark.slow
def test_resnet_learns():
    cfg = ResNetConfig(stage_sizes=(1, 1), width=8, num_classes=2, dtype=jnp.float32)
    model = ResNet(cfg)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8, 8, 3)).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    variables = model.init(jax.random.key(0), x)
    tx = optax.adam(1e-2)
    opt_state = tx.init(variables["params"])

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            logp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

        l, g = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, l

    params = variables["params"]
    losses = []
    for _ in range(30):
        params, opt_state, l = step(params, opt_state, x, y)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5


def test_bert_forward_and_masking():
    cfg = BertConfig.tiny()
    model = Bert(cfg)
    tokens = jnp.asarray(np.arange(16)[None, :] % cfg.vocab_size, dtype=jnp.int32)
    mask = jnp.ones_like(tokens).at[0, 10:].set(0)  # pad the tail
    variables = model.init(jax.random.key(0), tokens, mask)
    logits, seq = model.apply(variables, tokens, mask)
    assert logits.shape == (1, cfg.num_classes)
    assert seq.shape == (1, 16, cfg.d_model)
    # padding must not influence real positions
    tokens2 = tokens.at[0, 12].set(99)
    logits2, _ = model.apply(variables, tokens2, mask)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2), atol=1e-5)


def test_bert_ablation_factory():
    cfg = BertConfig.tiny()
    tokens = jnp.asarray(np.arange(8)[None, :], dtype=jnp.int32)

    full = Bert(cfg)
    v_full = full.init(jax.random.key(0), tokens)
    n_full = len(jax.tree.leaves(v_full))

    import dataclasses

    ablated = Bert(dataclasses.replace(cfg, ablated=frozenset({"layer_1", "pooler"})))
    v_abl = ablated.init(jax.random.key(0), tokens)
    n_abl = len(jax.tree.leaves(v_abl))
    assert n_abl < n_full
    assert "layer_1" not in v_abl["params"]
    assert "pooler" not in v_abl["params"]
    logits, _ = ablated.apply(v_abl, tokens)
    assert np.isfinite(np.asarray(logits)).all()
