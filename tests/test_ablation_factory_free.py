"""Factory-free model ablation (VERDICT r3 item 3): DecoderConfig.without
gating, the generic param-subtree masking fallback, and the driver's
auto-derivation — reference parity with Keras-JSON layer surgery
(loco.py:82-136) minus the user plumbing."""

import importlib
import tempfile

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu.ablation.masking import ParamMaskedModel, auto_ablate
from maggy_tpu.models import Decoder, DecoderConfig


def _tokens(cfg, b=2, s=16, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, (b, s)).astype(np.int32)


# --------------------------------------------------------------- cfg.without

def test_without_validates_and_merges():
    cfg = DecoderConfig.tiny()
    c2 = cfg.without("mlp").without(["layers.0", "layers.1.attn"])
    assert c2.ablated == frozenset({"mlp", "layers.0", "layers.1.attn"})
    with pytest.raises(ValueError, match="Unknown ablated component"):
        cfg.without("pooler")
    with pytest.raises(ValueError, match="out of range"):
        cfg.without("layers.7")
    with pytest.raises(ValueError, match="Unknown ablated component"):
        cfg.without("layers.0.norm")


def test_without_gates_match_zeroed_params():
    """Gating 'mlp' out must equal running the full model with every MLP
    param zeroed (zero-param SwiGLU outputs exactly zero), and differ from
    the baseline."""
    cfg = DecoderConfig.tiny()
    tokens = _tokens(cfg)
    model = Decoder(cfg)
    params = model.init(jax.random.key(0), tokens)["params"]

    base = model.apply({"params": params}, tokens)
    ablated = Decoder(cfg.without("mlp")).apply({"params": params}, tokens)
    assert not np.allclose(np.asarray(base), np.asarray(ablated))

    zeroed = jax.tree_util.tree_map_with_path(
        lambda p, leaf: jnp.zeros_like(leaf)
        if "mlp" in jax.tree_util.keystr(p) and "norm" not in jax.tree_util.keystr(p)
        else leaf,
        params,
    )
    ref = model.apply({"params": zeroed}, tokens)
    np.testing.assert_allclose(np.asarray(ablated), np.asarray(ref), atol=1e-5)


def test_without_single_layer_gate_unscanned_parity():
    """Per-layer gates must agree between the scanned and unscanned stacks."""
    # fp32: scan vs python-loop accumulate differently at bf16
    cfg = DecoderConfig.tiny(dtype=jnp.float32).without("layers.1")
    cfg_py = DecoderConfig.tiny(dtype=jnp.float32, scan_layers=False).without("layers.1")
    tokens = _tokens(cfg)
    scanned = Decoder(cfg)
    p = scanned.init(jax.random.key(0), tokens)["params"]
    out_scan = scanned.apply({"params": p}, tokens)

    # re-layout layer-stacked params into the unscanned tree
    unscanned = Decoder(cfg_py)
    p_py = unscanned.init(jax.random.key(0), tokens)["params"]
    from maggy_tpu.parallel.sharding import unbox

    pu, ps = unbox(p_py), unbox(p)
    rebuilt = dict(pu)
    for i in range(cfg.n_layers):
        rebuilt[f"layers_{i}"] = {
            "layer": jax.tree.map(lambda a, idx=i: a[idx], ps["layers"]["layer"])
        }
    rebuilt["embedding"] = ps["embedding"]
    rebuilt["final_norm"] = ps["final_norm"]
    rebuilt["lm_head"] = ps["lm_head"]
    out_py = unscanned.apply({"params": rebuilt}, tokens)
    np.testing.assert_allclose(
        np.asarray(out_scan), np.asarray(out_py), atol=1e-4
    )


def test_ablated_gradients_are_zero():
    cfg = DecoderConfig.tiny().without("layers.0.attn")
    tokens = _tokens(cfg)
    model = Decoder(cfg)
    params = model.init(jax.random.key(0), tokens)["params"]

    def loss(p):
        return model.apply({"params": p}, tokens).sum()

    grads = jax.grad(loss)(params)
    from maggy_tpu.parallel.sharding import unbox

    g = unbox(grads)["layers"]["layer"]["attn"]
    for leaf in jax.tree.leaves(g):
        assert float(jnp.abs(leaf[0]).max()) == 0.0  # layer 0: gated
        assert float(jnp.abs(leaf[1]).max()) > 0.0   # layer 1: live


# ----------------------------------------------------------- generic masking

class _PlainMLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        h = nn.Dense(8, name="hidden")(x)
        x = x + nn.Dense(x.shape[-1], name="proj")(nn.relu(h))
        return nn.Dense(2, name="head")(x)


def test_param_masked_model_zeroes_subtree_and_grads():
    base = _PlainMLP()
    x = jnp.ones((3, 4))
    masked = ParamMaskedModel(base, {"proj"})
    variables = masked.init(jax.random.key(0), x)

    ref_params = jax.tree_util.tree_map_with_path(
        lambda p, leaf: jnp.zeros_like(leaf)
        if "proj" in jax.tree_util.keystr(p)
        else leaf,
        base.init(jax.random.key(0), x)["params"],
    )
    np.testing.assert_allclose(
        np.asarray(masked.apply(variables, x)),
        np.asarray(base.apply({"params": ref_params}, x)),
        atol=1e-6,
    )

    def loss(v):
        return masked.apply(v, x).sum()

    g = jax.grad(loss)(variables)["params"]
    for leaf in jax.tree.leaves(g["proj"]):
        assert float(jnp.abs(leaf).max()) == 0.0
    assert any(float(jnp.abs(l).max()) > 0 for l in jax.tree.leaves(g["head"]))

    with pytest.raises(ValueError, match="no parameter subtree"):
        ParamMaskedModel(base, {"nonexistent"}).init(jax.random.key(0), x)


def test_moe_without_gates_forward():
    """MoEConfig inherits .without(); the MoEDecoder must actually honor the
    gates (an inherited-but-ignored ablated set would silently no-op)."""
    from maggy_tpu.models import MoEConfig, MoEDecoder

    cfg = MoEConfig.tiny_moe(dtype=jnp.float32)
    tokens = _tokens(cfg)
    model = MoEDecoder(cfg)
    params = model.init(jax.random.key(0), tokens)["params"]
    base = model.apply({"params": params}, tokens)
    ablated = MoEDecoder(cfg.without("layers.1")).apply({"params": params}, tokens)
    assert not np.allclose(np.asarray(base), np.asarray(ablated), atol=1e-5)
    # gating all layers' moe+attn leaves only embed -> norm -> head
    all_off = MoEDecoder(cfg.without(["attn", "mlp"])).apply(
        {"params": params}, tokens
    )
    assert not np.allclose(np.asarray(ablated), np.asarray(all_off), atol=1e-5)
    # the gate also silences the router aux loss of the ablated block
    from maggy_tpu.train.trainer import collect_aux_losses

    _, mods_abl = MoEDecoder(cfg.without("mlp")).apply(
        {"params": params}, tokens, mutable=["intermediates"]
    )
    _, mods_full = MoEDecoder(cfg).apply(
        {"params": params}, tokens, mutable=["intermediates"]
    )
    assert float(collect_aux_losses(mods_abl)) == 0.0
    assert float(collect_aux_losses(mods_full)) > 0.0


def test_auto_ablate_tiers():
    # tier 1: config with without()
    m = auto_ablate(Decoder(DecoderConfig.tiny()), frozenset({"mlp"}))
    assert isinstance(m, Decoder) and m.cfg.ablated == frozenset({"mlp"})
    # tier 2: config with an ablated field
    from maggy_tpu.models import Bert, BertConfig

    b = auto_ablate(Bert(BertConfig.tiny()), frozenset({"pooler"}))
    assert isinstance(b, Bert) and b.cfg.ablated == frozenset({"pooler"})
    # tier 3: plain module -> masking wrapper
    p = auto_ablate(_PlainMLP(), frozenset({"hidden"}))
    assert isinstance(p, ParamMaskedModel)


def test_default_dataset_generator_streaming_datasets(tmp_path):
    """Feature ablation on streaming datasets rebuilds a column-filtered
    view — no file rewrites, schema-style like the reference's feature-store
    drop (loco.py:41-80)."""
    from maggy_tpu.ablation.ablationstudy import default_dataset_generator
    from maggy_tpu.train.sharded_dataset import (
        ParquetShardedDataset,
        ShardedDataset,
        write_parquet,
        write_sharded,
    )

    data = {
        "tokens": np.arange(32, dtype=np.int32).reshape(8, 4),
        "extra": np.arange(8, dtype=np.int64),
    }
    write_sharded(str(tmp_path / "npy"), data, num_shards=2)
    ds = ShardedDataset(str(tmp_path / "npy"))
    dropped = default_dataset_generator(ds, "extra")
    assert dropped.fields == ["tokens"]
    assert next(dropped.loader(4, loop=False, shuffle=False)).keys() == {"tokens"}

    pytest.importorskip("pyarrow")
    write_parquet(str(tmp_path / "pq"), data, rows_per_group=4)
    pq_ds = ParquetShardedDataset(str(tmp_path / "pq"))
    pq_dropped = default_dataset_generator(pq_ds, "extra")
    assert pq_dropped.fields == ["tokens"]

    with pytest.raises(KeyError):
        default_dataset_generator(ds, "nope")
    with pytest.raises(ValueError):
        default_dataset_generator(dropped, "tokens")  # only field left


# ------------------------------------------------------------- driver e2e

def test_loco_lagom_zero_factories():
    """Full lagom ablation run with NO set_factory: variants derived from
    AblationConfig(model=...) automatically."""
    experiment = importlib.import_module("maggy_tpu.experiment")
    from maggy_tpu.ablation import AblationStudy
    from maggy_tpu.config import AblationConfig
    from maggy_tpu.core import env as env_mod
    from maggy_tpu.core.env.base import BaseEnv

    env_mod.set_instance(BaseEnv(tempfile.mkdtemp()))
    try:
        cfg = DecoderConfig.tiny()
        tokens = _tokens(cfg, b=4, s=8)
        seen = []

        def train(model, reporter):
            params = model.init(jax.random.key(0), tokens)["params"]
            out = model.apply({"params": params}, tokens)
            seen.append(getattr(model.cfg, "ablated", frozenset()))
            metric = float(jnp.abs(out).mean())
            reporter.broadcast(metric, step=0)
            return metric

        study = AblationStudy()
        study.model.layers.include("mlp", "layers.0")
        result = experiment.lagom(
            train,
            AblationConfig(
                ablation_study=study,
                model=Decoder(cfg),
                direction="max",
                hb_interval=0.05,
            ),
        )
        assert result["num_trials"] == 3  # baseline + 2 components
        assert frozenset() in seen
        assert frozenset({"mlp"}) in seen
        assert frozenset({"layers.0"}) in seen
    finally:
        env_mod.set_instance(None)
