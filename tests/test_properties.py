"""Property-based tests: searchspace transform bijectivity over arbitrary
spaces, trial JSON round-trips, RPC framing, ShardingSpec algebra.

The randomized-generation tests use hypothesis when it is installed; on
images without it they individually skip (the module must still collect —
the exhaustive ShardingSpec preset/scaled_to/_largest_factor_leq property
tests below are hypothesis-free and always run)."""

import json
import string

import numpy as np
import pytest

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
except ModuleNotFoundError:  # hypothesis not in the runtime image

    class _AnyStrategy:
        """Stand-in for the strategies module/strategy objects: absorbs any
        module-scope strategy construction so decorated tests still define,
        then skip at call time."""

        def __call__(self, *args, **kwargs):
            return self

        def __getattr__(self, name):
            return self

    st = _AnyStrategy()

    def given(*_args, **_kwargs):
        def deco(fn):
            # zero-arg replacement (no functools.wraps: pytest would read
            # the original signature and hunt for fixtures named like the
            # hypothesis-injected parameters)
            def wrapper():
                pytest.skip("hypothesis not installed")

            wrapper.__name__ = fn.__name__
            wrapper.__doc__ = fn.__doc__
            return wrapper

        return deco

    def settings(*_args, **_kwargs):
        return lambda fn: fn

from maggy_tpu import Searchspace, Trial
from maggy_tpu.parallel.spec import ShardingSpec, _largest_factor_leq

NAMES = st.text(alphabet=string.ascii_lowercase, min_size=1, max_size=8).filter(
    lambda s: not hasattr(Searchspace, s)
)


@st.composite
def searchspaces(draw):
    n = draw(st.integers(1, 4))
    names = draw(
        st.lists(NAMES, min_size=n, max_size=n, unique=True)
    )
    space = Searchspace()
    for name in names:
        kind = draw(st.sampled_from(["DOUBLE", "INTEGER", "DISCRETE", "CATEGORICAL"]))
        if kind == "DOUBLE":
            lo = draw(st.floats(-1e6, 1e6, allow_nan=False))
            hi = draw(st.floats(lo + 1e-6, lo + 1e7, allow_nan=False))
            space.add(name, (kind, [lo, hi]))
        elif kind == "INTEGER":
            lo = draw(st.integers(-10_000, 10_000))
            hi = draw(st.integers(lo + 1, lo + 20_000))
            space.add(name, (kind, [lo, hi]))
        elif kind == "DISCRETE":
            vals = draw(
                st.lists(st.integers(-1000, 1000), min_size=1, max_size=6, unique=True)
            )
            space.add(name, (kind, vals))
        else:
            vals = draw(
                st.lists(
                    st.text(string.ascii_letters, min_size=1, max_size=5),
                    min_size=1,
                    max_size=6,
                    unique=True,
                )
            )
            space.add(name, (kind, vals))
    return space


@settings(max_examples=60, deadline=None, derandomize=True)
@given(searchspaces(), st.integers(0, 2**31 - 1))
def test_transform_roundtrip_property(space, seed):
    params = space.get_random_parameter_values(1, seed=seed)[0]
    vec = space.transform(params)
    assert ((vec >= 0) & (vec <= 1)).all()
    back = space.inverse_transform(vec)
    for item in space.items():
        name, kind = item["name"], item["type"]
        if kind == "DOUBLE":
            scale = max(abs(v) for v in item["values"]) or 1.0
            assert abs(back[name] - params[name]) <= 1e-9 * scale + 1e-12
        else:
            assert back[name] == params[name]


@settings(max_examples=60, deadline=None, derandomize=True)
@given(searchspaces(), st.lists(st.floats(0, 1), min_size=4, max_size=4))
def test_any_cube_point_decodes_valid(space, coords):
    vec = np.asarray(coords[: len(space)])
    params = space.inverse_transform(vec)
    assert space.contains(params)


@settings(max_examples=60, deadline=None, derandomize=True)
@given(searchspaces(), st.integers(0, 2**31 - 1))
def test_trial_json_roundtrip_property(space, seed):
    params = space.get_random_parameter_values(1, seed=seed)[0]
    t = Trial(params)
    for s in range(seed % 4):
        t.append_metric(float(s) * 0.1, step=s)
    if seed % 2:
        t.finalize(1.5)
    t2 = Trial.from_json(t.to_json())
    assert t2.trial_id == t.trial_id
    assert t2.status == t.status
    assert t2.metric_history == t.metric_history
    # canonical id is stable under key reordering
    assert Trial.compute_id(dict(reversed(list(params.items())))) == t.trial_id


@settings(max_examples=100, deadline=None, derandomize=True)
@given(
    st.integers(1, 8), st.integers(1, 8), st.integers(1, 8),
    st.integers(1, 4), st.integers(1, 4), st.integers(1, 4),
)
def test_sharding_spec_algebra(dp, fsdp, tp, sp, ep, pp):
    spec = ShardingSpec(dp=dp, fsdp=fsdp, tp=tp, sp=sp, ep=ep, pp=pp)
    assert spec.num_devices == dp * fsdp * tp * sp * ep * pp
    sizes = spec.axis_sizes()
    assert np.prod(sizes) == spec.num_devices
    # scaled_to is identity when already matching, and always exact when divisible
    assert spec.scaled_to(spec.num_devices) == spec
    bigger = spec.num_devices * 3
    scaled = spec.scaled_to(bigger)
    assert scaled.num_devices == bigger


@settings(max_examples=60, deadline=None, derandomize=True)
@given(
    st.dictionaries(
        st.text(string.ascii_lowercase, min_size=1, max_size=6),
        st.one_of(
            st.integers(-(2**31), 2**31),
            st.floats(allow_nan=False, allow_infinity=False),
            st.text(max_size=20),
            st.booleans(),
            st.none(),
        ),
        max_size=6,
    )
)
def test_rpc_frame_roundtrip_property(payload):
    """Framed JSON messages survive a socketpair round-trip byte-exactly."""
    import socket

    from maggy_tpu.core import rpc

    a, b = socket.socketpair()
    try:
        msg = {"type": "ECHO", **{f"k_{k}": v for k, v in payload.items()}}
        rpc.send_frame(a, msg)
        out = rpc.recv_frame(b)
        assert out == json.loads(json.dumps(msg))
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------- ShardingSpec
# Exhaustive (hypothesis-free) property sweeps: small domains make full
# enumeration cheaper and stronger than sampled generation, and they run on
# images without hypothesis.

PRESETS = ("dp", "ddp", "fsdp", "zero", "zero3", "tp", "sp", "pp", "2d", "ep")


@pytest.mark.parametrize("name", PRESETS)
def test_preset_axis_product_covers_devices(name):
    """Every preset, for every device count 1..64: the axis product equals
    num_devices exactly (nothing silently dropped or replicated)."""
    for n in range(1, 65):
        spec = ShardingSpec.preset(name, n)
        assert spec.num_devices == n
        assert int(np.prod(spec.axis_sizes())) == n


@pytest.mark.parametrize("name", ("2d", "ep"))
def test_preset_inner_axis_cap_respected(name):
    """The 2d/ep presets cap their inner axis at floor(sqrt(n)) and give the
    remainder to fsdp; both axes must divide n."""
    for n in range(1, 129):
        spec = ShardingSpec.preset(name, n)
        inner = spec.tp if name == "2d" else spec.ep
        cap = max(1, int(n**0.5))
        assert 1 <= inner <= cap
        assert n % inner == 0
        assert spec.fsdp == n // inner


def test_largest_factor_leq_properties():
    """_largest_factor_leq(n, cap): divides n, respects the cap, and is
    MAXIMAL — no larger factor under the cap exists. Full sweep n, cap in
    1..128."""
    for n in range(1, 129):
        for cap in range(1, 129):
            f = _largest_factor_leq(n, cap)
            assert 1 <= f <= max(1, min(cap, n))
            assert n % f == 0
            assert not any(
                n % g == 0 for g in range(f + 1, min(cap, n) + 1)
            ), (n, cap, f)


def test_scaled_to_idempotent_rescale():
    """scaled_to is exact and idempotent: rescaling to the same target is a
    fixed point, and any divisible target is hit exactly."""
    specs = [
        ShardingSpec(),
        ShardingSpec(dp=2),
        ShardingSpec(fsdp=4),
        ShardingSpec(fsdp=2, tp=2),
        ShardingSpec(dp=2, fsdp=2, tp=2),
        ShardingSpec(pp=2, dp=2),
        ShardingSpec(ep=2, fsdp=2),
        ShardingSpec(sp=2, tp=2),
    ]
    for spec in specs:
        rest = spec.fsdp * spec.tp * spec.sp * spec.ep * spec.pp
        for mult in (1, 2, 3, 5, 8):
            target = rest * mult
            scaled = spec.scaled_to(target)
            assert scaled.num_devices == target
            # idempotent: a second rescale to the same target changes nothing
            assert scaled.scaled_to(target) == scaled
            # non-dp axes never move
            assert (scaled.fsdp, scaled.tp, scaled.sp, scaled.ep, scaled.pp) == (
                spec.fsdp, spec.tp, spec.sp, spec.ep, spec.pp
            )
        # indivisible targets refuse loudly rather than mis-shard
        if rest > 1:
            with pytest.raises(ValueError):
                spec.scaled_to(rest + 1)
