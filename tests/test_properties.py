"""Property-based tests (hypothesis): searchspace transform bijectivity over
arbitrary spaces, trial JSON round-trips, RPC framing, ShardingSpec algebra."""

import json
import string

import numpy as np
from hypothesis import given, settings
from hypothesis import strategies as st

from maggy_tpu import Searchspace, Trial
from maggy_tpu.parallel.spec import ShardingSpec

NAMES = st.text(alphabet=string.ascii_lowercase, min_size=1, max_size=8).filter(
    lambda s: not hasattr(Searchspace, s)
)


@st.composite
def searchspaces(draw):
    n = draw(st.integers(1, 4))
    names = draw(
        st.lists(NAMES, min_size=n, max_size=n, unique=True)
    )
    space = Searchspace()
    for name in names:
        kind = draw(st.sampled_from(["DOUBLE", "INTEGER", "DISCRETE", "CATEGORICAL"]))
        if kind == "DOUBLE":
            lo = draw(st.floats(-1e6, 1e6, allow_nan=False))
            hi = draw(st.floats(lo + 1e-6, lo + 1e7, allow_nan=False))
            space.add(name, (kind, [lo, hi]))
        elif kind == "INTEGER":
            lo = draw(st.integers(-10_000, 10_000))
            hi = draw(st.integers(lo + 1, lo + 20_000))
            space.add(name, (kind, [lo, hi]))
        elif kind == "DISCRETE":
            vals = draw(
                st.lists(st.integers(-1000, 1000), min_size=1, max_size=6, unique=True)
            )
            space.add(name, (kind, vals))
        else:
            vals = draw(
                st.lists(
                    st.text(string.ascii_letters, min_size=1, max_size=5),
                    min_size=1,
                    max_size=6,
                    unique=True,
                )
            )
            space.add(name, (kind, vals))
    return space


@settings(max_examples=60, deadline=None, derandomize=True)
@given(searchspaces(), st.integers(0, 2**31 - 1))
def test_transform_roundtrip_property(space, seed):
    params = space.get_random_parameter_values(1, seed=seed)[0]
    vec = space.transform(params)
    assert ((vec >= 0) & (vec <= 1)).all()
    back = space.inverse_transform(vec)
    for item in space.items():
        name, kind = item["name"], item["type"]
        if kind == "DOUBLE":
            scale = max(abs(v) for v in item["values"]) or 1.0
            assert abs(back[name] - params[name]) <= 1e-9 * scale + 1e-12
        else:
            assert back[name] == params[name]


@settings(max_examples=60, deadline=None, derandomize=True)
@given(searchspaces(), st.lists(st.floats(0, 1), min_size=4, max_size=4))
def test_any_cube_point_decodes_valid(space, coords):
    vec = np.asarray(coords[: len(space)])
    params = space.inverse_transform(vec)
    assert space.contains(params)


@settings(max_examples=60, deadline=None, derandomize=True)
@given(searchspaces(), st.integers(0, 2**31 - 1))
def test_trial_json_roundtrip_property(space, seed):
    params = space.get_random_parameter_values(1, seed=seed)[0]
    t = Trial(params)
    for s in range(seed % 4):
        t.append_metric(float(s) * 0.1, step=s)
    if seed % 2:
        t.finalize(1.5)
    t2 = Trial.from_json(t.to_json())
    assert t2.trial_id == t.trial_id
    assert t2.status == t.status
    assert t2.metric_history == t.metric_history
    # canonical id is stable under key reordering
    assert Trial.compute_id(dict(reversed(list(params.items())))) == t.trial_id


@settings(max_examples=100, deadline=None, derandomize=True)
@given(
    st.integers(1, 8), st.integers(1, 8), st.integers(1, 8),
    st.integers(1, 4), st.integers(1, 4), st.integers(1, 4),
)
def test_sharding_spec_algebra(dp, fsdp, tp, sp, ep, pp):
    spec = ShardingSpec(dp=dp, fsdp=fsdp, tp=tp, sp=sp, ep=ep, pp=pp)
    assert spec.num_devices == dp * fsdp * tp * sp * ep * pp
    sizes = spec.axis_sizes()
    assert np.prod(sizes) == spec.num_devices
    # scaled_to is identity when already matching, and always exact when divisible
    assert spec.scaled_to(spec.num_devices) == spec
    bigger = spec.num_devices * 3
    scaled = spec.scaled_to(bigger)
    assert scaled.num_devices == bigger


@settings(max_examples=60, deadline=None, derandomize=True)
@given(
    st.dictionaries(
        st.text(string.ascii_lowercase, min_size=1, max_size=6),
        st.one_of(
            st.integers(-(2**31), 2**31),
            st.floats(allow_nan=False, allow_infinity=False),
            st.text(max_size=20),
            st.booleans(),
            st.none(),
        ),
        max_size=6,
    )
)
def test_rpc_frame_roundtrip_property(payload):
    """Framed JSON messages survive a socketpair round-trip byte-exactly."""
    import socket

    from maggy_tpu.core import rpc

    a, b = socket.socketpair()
    try:
        msg = {"type": "ECHO", **{f"k_{k}": v for k, v in payload.items()}}
        rpc.send_frame(a, msg)
        out = rpc.recv_frame(b)
        assert out == json.loads(json.dumps(msg))
    finally:
        a.close()
        b.close()
