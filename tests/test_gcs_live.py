"""The PRODUCTION gs:// path (VERDICT r4 item 7): the gcsfs driver is
actually instantiated — no longer dead code behind the memory:// CI seam —
with error paths for a missing driver, and live read/write coverage that
engages whenever the environment can reach GCS (env-gated on a bucket for
authenticated round-trips; anonymous public-bucket reads skip themselves on
zero-egress CI). Reference analogue: the HDFS/REST environment the upstream
project runs against live infrastructure (core/environment/hopsworks.py:
81-103)."""

import os

import pytest

from maggy_tpu.core.env.gcs import GcsEnv


def test_gs_driver_instantiates_real_gcsfs():
    """GcsEnv('gs://...') must construct the real gcsfs filesystem object —
    construction is local (no network), so this runs everywhere and proves
    the production protocol wiring end-to-end up to the socket."""
    gcsfs = pytest.importorskip("gcsfs")
    env = GcsEnv("gs://maggy-tpu-it-bucket/prefix")
    assert env.protocol == "gs"
    assert isinstance(env.fs, gcsfs.GCSFileSystem)
    # path helpers compose gs:// URLs, not local paths
    assert env.experiment_dir("app_1", 1).startswith("gs://maggy-tpu-it-bucket")


def test_missing_driver_is_a_clear_error():
    env = GcsEnv("no_such_proto://bucket")
    with pytest.raises(RuntimeError, match="no_such_proto"):
        env.fs


def _is_connectivity_error(exc: BaseException) -> bool:
    """Walk the cause chain for network-unreachable classes (DNS failure,
    connection refused, timeouts) — vs GCS-side errors, which mean egress
    worked and a failure is real."""
    import socket

    names = (
        "ClientConnectorError", "ClientConnectorDNSError", "ClientOSError",
        "ServerTimeoutError", "ConnectTimeoutError",
    )
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        # NOT bare OSError: gcsfs maps GCS-side failures to OSError
        # subclasses (FileNotFoundError, PermissionError) that must FAIL
        if isinstance(exc, (socket.gaierror, ConnectionError, TimeoutError)):
            return True
        if type(exc).__name__ in names:
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def test_gs_anon_public_read():
    """Read a well-known public bucket anonymously (gcsfs token='anon').
    Zero-egress environments skip themselves — only CONNECTIVITY failures
    are a skip; a GCS-side error with working egress fails the test."""
    gcsfs = pytest.importorskip("gcsfs")
    fs = gcsfs.GCSFileSystem(token="anon")
    try:
        listing = fs.ls("gcp-public-data-landsat")
    except Exception as e:  # noqa: BLE001 - classified below
        if _is_connectivity_error(e):
            pytest.skip(
                f"no egress to GCS from this environment: {type(e).__name__}: {e}"
            )
        raise
    assert listing, "public bucket listed empty"


needs_bucket = pytest.mark.skipif(
    not os.environ.get("MAGGY_TPU_GCS_TEST_BUCKET"),
    reason="set MAGGY_TPU_GCS_TEST_BUCKET=gs://<bucket>/<prefix> (with "
    "application-default credentials) to run the live GCS round-trip",
)


@needs_bucket
def test_gs_live_round_trip():
    """Authenticated write/list/read/delete against a real bucket — the
    full Env surface the experiments use (dump, registry, listdir)."""
    import uuid

    root = os.environ["MAGGY_TPU_GCS_TEST_BUCKET"].rstrip("/")
    env = GcsEnv(f"{root}/maggy-it-{uuid.uuid4().hex[:8]}")
    try:
        env.register_driver("app_it", 1, "host", 1234, secret="s", scope="pod")
        rec = env.lookup_driver("app_it")
        assert rec and rec["port"] == 1234
        path = env.root + "/blob.json"
        env.dump({"x": 1}, path)
        with env.open_file(path) as f:
            assert "\"x\"" in f.read()
        assert any("blob.json" in p for p in env.listdir(env.root))
    finally:
        env.delete(env.root, recursive=True)
