"""Pod-mode HPO: remote trial executors + worker capacity recovery (VERDICT
r4 item 3). The reference gets cross-host trial executors and failed-task
re-execution from Spark (spark_driver.py:136-145, rpc.py:415-437); here any
host running the same script with MAGGY_TPU_ROLE=worker adds trial capacity,
a killed worker's trial is freed (re-registration or liveness timeout), and
a respawned worker rejoins the live experiment."""

import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig

pytestmark = pytest.mark.slow  # subprocess/multi-process tier

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

HPO_WORKER_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from maggy_tpu import Searchspace, experiment
    from maggy_tpu.config import HyperparameterOptConfig

    def train(hparams, reporter):
        reporter.broadcast(float(hparams["x"]), step=0)
        time.sleep({trial_s})
        return {{"metric": float(hparams["x"])}}

    result = experiment.lagom(
        train,
        HyperparameterOptConfig(
            num_trials=10,
            optimizer="randomsearch",
            searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
            direction="max",
            es_policy="none",
            num_executors=2,
            hb_interval=0.05,
        ),
    )
    print("WORKER-DONE", result, flush=True)
    """
)


def _driver_config(worker_timeout=600.0, num_trials=30):
    return HyperparameterOptConfig(
        num_trials=num_trials,
        optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
        direction="max",
        es_policy="none",
        num_executors=2,
        hb_interval=0.05,
        driver_addr="127.0.0.1:auto",  # placeholder: flags pod mode
        worker_timeout=worker_timeout,
    )


def _start_driver(result_holder, worker_timeout=600.0, trial_s=0.3, num_trials=30):
    def train(hparams, reporter):
        reporter.broadcast(float(hparams["x"]), step=0)
        time.sleep(trial_s)
        return {"metric": float(hparams["x"])}

    def run_driver():
        try:
            result_holder["result"] = experiment.lagom(
                train, _driver_config(worker_timeout, num_trials)
            )
        except BaseException as e:  # noqa: BLE001 - surfaced by the test
            result_holder["error"] = e

    t = threading.Thread(target=run_driver)
    t.start()
    deadline = time.time() + 30
    driver = None
    while time.time() < deadline:
        driver = experiment.CURRENT_DRIVER
        if driver is not None and driver.server is not None and driver.server.port:
            break
        time.sleep(0.05)
    assert driver is not None and driver.server is not None, "driver never started"
    assert driver.pod_mode
    return t, driver


def _worker_env(driver, tmp_path, partition="1"):
    env = dict(os.environ)
    env.update(
        {
            "MAGGY_TPU_ROLE": "worker",
            "MAGGY_TPU_DRIVER": f"127.0.0.1:{driver.server.port}",
            "MAGGY_TPU_SECRET": driver.server.secret,
            "MAGGY_TPU_PARTITION": partition,
            "MAGGY_TPU_LOG_ROOT": os.environ.get("MAGGY_TPU_LOG_ROOT", str(tmp_path)),
        }
    )
    return env


def _spawn_worker(script_path, env):
    return subprocess.Popen(
        [sys.executable, str(script_path)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def test_pod_hpo_worker_kill_and_respawn_completes_all_trials(tmp_env, tmp_path):
    """Kill a remote trial worker mid-ASHA-style run, respawn it (what
    ``maggy_tpu.run --respawn`` does): the respawned worker re-registers
    (fresh attempt nonce), the lost trial is freed, and the experiment ends
    with the FULL trial count."""
    result_holder = {}
    t, driver = _start_driver(result_holder, trial_s=0.4)

    script = tmp_path / "worker.py"
    script.write_text(HPO_WORKER_SCRIPT.format(repo=REPO, trial_s=0.4))
    env = _worker_env(driver, tmp_path)

    victim = _spawn_worker(script, env)
    time.sleep(2.0)  # well into the 30x0.4s trial stream
    victim.kill()
    victim.wait(timeout=30)

    # capacity recovery: the supervisor's respawn, into the LIVE experiment
    replacement = _spawn_worker(script, env)
    out, _ = replacement.communicate(timeout=120)
    assert replacement.returncode == 0, out[-2000:]

    t.join(timeout=120)
    assert not t.is_alive(), "driver did not finish"
    assert "error" not in result_holder, result_holder.get("error")
    result = result_holder["result"]
    # full trial count: budget completes despite the kill; at most the one
    # in-flight trial is ERROR (reference loses exactly the in-flight task)
    assert result["num_trials"] == 30
    assert result.get("errors", 0) <= 1
    assert result["best"] is not None


def test_pod_hpo_dead_worker_liveness_frees_trial_and_completes(tmp_env, tmp_path):
    """No respawn at all: the liveness sweep (worker_timeout) frees the dead
    worker's trial and the remaining capacity finishes the budget — the
    driver must NOT hang or abort."""
    result_holder = {}
    t, driver = _start_driver(
        result_holder, worker_timeout=2.0, trial_s=0.3, num_trials=20
    )

    script = tmp_path / "worker.py"
    script.write_text(HPO_WORKER_SCRIPT.format(repo=REPO, trial_s=0.3))
    victim = _spawn_worker(script, _worker_env(driver, tmp_path))
    time.sleep(2.0)
    victim.kill()
    victim.wait(timeout=30)

    t.join(timeout=120)
    assert not t.is_alive(), "driver hung after worker death"
    assert "error" not in result_holder, result_holder.get("error")
    result = result_holder["result"]
    assert result["num_trials"] == 20
    assert result.get("errors", 0) <= 1


RESPAWN_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    sys.path.insert(0, {repo!r})
    sentinel = {sentinel!r}
    if os.environ.get("MAGGY_TPU_ROLE") == "worker" and not os.path.exists(sentinel):
        open(sentinel, "w").close()
        sys.exit(3)  # simulated crash before joining
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from maggy_tpu import Searchspace, experiment
    from maggy_tpu.config import HyperparameterOptConfig

    def train(hparams, reporter):
        reporter.broadcast(float(hparams["x"]), step=0)
        time.sleep(0.1)
        return {{"metric": float(hparams["x"])}}

    result = experiment.lagom(
        train,
        HyperparameterOptConfig(
            num_trials=40,
            optimizer="randomsearch",
            searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
            direction="max",
            es_policy="none",
            num_executors=2,
            hb_interval=0.05,
        ),
    )
    print("RESULT", result, flush=True)
    """
)


def test_run_launcher_respawn_recovers_worker(tmp_path):
    """`python -m maggy_tpu.run --respawn`: a worker rank that dies is
    respawned into the LIVE experiment (driver keeps running) and the run
    completes all trials."""
    sentinel = str(tmp_path / "crashed_once")
    script = tmp_path / "user_script.py"
    script.write_text(RESPAWN_SCRIPT.format(repo=REPO, sentinel=sentinel))
    env = dict(os.environ)
    env["MAGGY_TPU_LOG_ROOT"] = str(tmp_path / "logs")
    env["MAGGY_TPU_CONNECT_TIMEOUT"] = "30"  # bound a worker-vs-done race
    proc = subprocess.run(
        [
            sys.executable, "-m", "maggy_tpu.run",
            "--workers", "2", "--respawn", "2", str(script),
        ],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert os.path.exists(sentinel), "worker never took the crash path"
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    # both ranks print RESULT: the driver's carries the study summary, the
    # worker's its role marker
    result_lines = [l for l in proc.stdout.splitlines() if l.startswith("RESULT")]
    assert result_lines, proc.stdout[-2000:]
    assert any("'num_trials': 40" in l for l in result_lines), result_lines
    assert any("'role': 'trial_worker'" in l for l in result_lines), result_lines
    assert "respawning into the live experiment" in proc.stderr


LEASE_WORKER_SCRIPT = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # this worker actually touches jax (device lease): pin through force_cpu
    # or the axon plugin's backend init can wedge even env-pinned processes
    from maggy_tpu.util import pin_cpu_if_requested
    pin_cpu_if_requested()
    import jax

    from maggy_tpu import Searchspace, experiment
    from maggy_tpu.config import HyperparameterOptConfig

    SERVED = [0]

    def train(hparams, reporter, ctx, devices):
        # the lease must be exactly the two devices named in
        # MAGGY_TPU_WORKER_DEVICES, and the injected ctx's mesh spans it
        assert len(devices) == 2, devices
        assert len(list(ctx.mesh.devices.flat)) == 2
        SERVED[0] += 1
        reporter.broadcast(float(hparams["x"]), step=0)
        return {{"metric": float(hparams["x"])}}

    result = experiment.lagom(
        train,
        HyperparameterOptConfig(
            num_trials=4,
            optimizer="randomsearch",
            searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
            direction="max",
            es_policy="none",
            num_executors=2,
            hb_interval=0.05,
        ),
    )
    print("LEASE-WORKER-DONE served", SERVED[0], flush=True)
    """
)


def test_pod_worker_device_lease(tmp_env, tmp_path):
    """MAGGY_TPU_WORKER_DEVICES leases a sub-slice of the worker host's
    devices to the remote trial executor — several workers can share one
    host, each trial training on its own devices."""
    result_holder = {}
    t, driver = _start_driver(result_holder, trial_s=0.4, num_trials=30)

    script = tmp_path / "worker.py"
    script.write_text(LEASE_WORKER_SCRIPT.format(repo=REPO))
    env = _worker_env(driver, tmp_path)
    env["MAGGY_TPU_WORKER_DEVICES"] = "1,2"
    worker = _spawn_worker(script, env)
    out, _ = worker.communicate(timeout=120)
    assert worker.returncode == 0, out[-2000:]
    assert "LEASE-WORKER-DONE" in out
    served = int(out.split("LEASE-WORKER-DONE served")[1].split()[0])
    assert served > 0, out[-1500:]  # the lease asserts must have actually run

    t.join(timeout=120)
    assert "error" not in result_holder, result_holder.get("error")
    assert result_holder["result"]["num_trials"] == 30
