"""Hyperband pruner tests: bracket geometry, promotion ranking, straggler
IDLE behavior, and a full lagom e2e run with the pruner attached."""

import pytest

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig
from maggy_tpu.pruner.hyperband import Hyperband


def test_bracket_geometry():
    metrics = {}
    hb = Hyperband(lambda ids: {i: metrics.get(i) for i in ids if i in metrics},
                   eta=3, resource_min=1, resource_max=9)
    # s_max = 2 -> brackets s=2,1,0
    caps = [[r.capacity for r in b.rungs] for b in hb.brackets]
    budgets = [[r.budget for r in b.rungs] for b in hb.brackets]
    assert caps == [[9, 3, 1], [5, 1], [3]]
    assert budgets == [[1, 3, 9], [3, 9], [9]]
    assert hb.num_trials() == 9 + 3 + 1 + 5 + 1 + 3


def test_promotion_respects_direction_and_errors():
    finished = {}
    hb = Hyperband(lambda ids: {i: finished[i] for i in ids if i in finished},
                   eta=2, resource_min=1, resource_max=2, direction="max")
    # single bracket rungs: [2,1] at budgets [1,2] + bracket s=0: [2] at [2]
    d = hb.pruning_routine()
    assert d == {"trial_id": None, "budget": 1}
    hb.report_trial(None, "t0")
    d = hb.pruning_routine()
    assert d == {"trial_id": None, "budget": 1}
    hb.report_trial(None, "t1")
    # rung 0 full but unfinished -> the s=0 bracket's base rung fills next
    d = hb.pruning_routine()
    assert d["trial_id"] is None and d["budget"] == 2
    hb.report_trial(None, "t2")
    d = hb.pruning_routine()
    assert d["trial_id"] is None and d["budget"] == 2
    hb.report_trial(None, "t3")
    # everything scheduled except promotion slot; stragglers -> IDLE
    assert hb.pruning_routine() == "IDLE"
    finished["t0"] = 0.1
    finished["t1"] = None  # errored trial counts as finished, ranked worst
    d = hb.pruning_routine()
    assert d == {"trial_id": "t0", "budget": 2}
    hb.report_trial("t0", "t0b")
    # every slot scheduled -> schedule exhausted (None) even while trials run;
    # the driver itself waits for in-flight trials to finalize
    assert hb.pruning_routine() is None


def test_pending_must_be_reported():
    hb = Hyperband(lambda ids: {}, eta=2, resource_min=1, resource_max=2)
    d = hb.pruning_routine()
    assert d["trial_id"] is None
    assert hb.pruning_routine() == "IDLE"  # decision not yet reported
    hb.report_trial(None, "x")
    assert hb.pruning_routine()["trial_id"] is None


def test_validation():
    with pytest.raises(ValueError):
        Hyperband(lambda ids: {}, eta=1)
    with pytest.raises(ValueError):
        Hyperband(lambda ids: {}, resource_min=5, resource_max=2)
    with pytest.raises(ValueError):
        Hyperband(lambda ids: {}, iterations=0)


def test_iterations_prevent_straggler_starvation():
    """With iterations=2, a fleet blocked on cycle-1 stragglers keeps
    getting fresh base-rung configs from cycle 2 instead of IDLE (the
    reference's concurrent-SH-iterations throughput semantics,
    hyperband.py:137-195)."""
    finished = {}
    hb = Hyperband(
        lambda ids: {i: finished[i] for i in ids if i in finished},
        eta=2, resource_min=1, resource_max=2, iterations=2,
    )
    assert hb.num_trials() == 2 * (2 + 1 + 2)
    # fill cycle 1 completely (both brackets' base rungs)
    for n in range(4):
        d = hb.pruning_routine()
        assert d["trial_id"] is None
        hb.report_trial(None, f"c1_{n}")
    # cycle 1's promotion is straggler-blocked, but cycle 2 must still yield
    for n in range(4):
        d = hb.pruning_routine()
        assert d is not None and d != "IDLE", "second cycle starved"
        assert d["trial_id"] is None
        hb.report_trial(None, f"c2_{n}")
    # now everything left is promotion slots behind stragglers -> IDLE
    assert hb.pruning_routine() == "IDLE"
    # cycle-1 stragglers finish: its promotion unblocks first
    finished.update({"c1_0": 0.9, "c1_1": 0.2})
    d = hb.pruning_routine()
    assert d == {"trial_id": "c1_0", "budget": 2}


def test_lagom_hyperband_e2e(tmp_env):
    budgets_seen = []

    def train(hparams, budget, reporter):
        budgets_seen.append(budget)
        for step in range(int(budget)):
            reporter.broadcast(hparams["x"], step=step)
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=1,  # overridden by the pruner schedule
        optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
        direction="max",
        num_executors=4,
        es_policy="none",
        hb_interval=0.05,
        pruner="hyperband",
        pruner_config={"eta": 3, "resource_min": 1, "resource_max": 9},
        seed=7,
    )
    result = experiment.lagom(train, cfg)
    assert result["num_trials"] == 9 + 3 + 1 + 5 + 1 + 3
    assert set(budgets_seen) == {1, 3, 9}
    assert result["errors"] == 0


@pytest.mark.slow
def test_hyperband_fleet_scale_stress():
    """VERDICT r4 item 6: 16 simulated executors, ~264 trials, 5%
    stragglers, through the REAL controllers (the driver's one-decision-
    at-a-time discipline). Locks three facts: concurrent cycles
    (iterations=N) beat the pre-knob serial-cycle behavior on both idle
    fraction and makespan under stragglers; and the controller sustains
    far more decisions/sec than a 16-executor fleet can consume — the
    _pending gate is consumed within one get_suggestion call and never
    throttles."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    from stress_hyperband import run_suite

    r = run_suite(n_executors=16, straggler=0.05, cycles=12)
    conc = r["hyperband_concurrent_cycles"]
    serial = r["hyperband_serial_cycles"]
    assert conc["trials"] == serial["trials"]
    assert conc["idle_fraction"] < serial["idle_fraction"] - 0.25
    assert conc["makespan"] < 0.7 * serial["makespan"]
    # the controller must beat the fleet's own consumption rate (one
    # decision per 6.25ms for 16 executors at 100ms/trial; measured
    # ~0.5ms). Under sys.settrace-style instrumentation (coverage), pure-
    # Python loops slow 10-30x — keep a backstop bound there instead of
    # flaking, so an accidental O(n^2) controller loop still trips it
    import sys as _sys

    bound_us = 50_000 if _sys.gettrace() is not None else 6_250
    assert conc["controller_s_per_decision_us"] < bound_us
