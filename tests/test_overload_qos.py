"""Overload robustness (ISSUE 15): per-tenant QoS, priority preemption,
gray-failure circuit breakers, and the deterministic traffic-replay harness.

The two acceptance demos ARE the issue criteria and carry the only engine
work in this module:

* ``test_overload_replay_acceptance`` — a seeded 2-class replay at ~2x
  capacity drives the brownout ladder through every level while premium
  holds its TTFT SLO and its completed streams stay byte-identical to an
  unloaded single-engine decode, despite priority preemptions.
* ``test_gray_failure_breaker_acceptance`` — ``replica_slow`` chaos on one
  of two replicas opens its circuit breaker, dispatch drains to the healthy
  peer with zero failed requests, and a half-open probation probe closes
  the breaker once the chaos clears.

Everything else (queue ordering, quota ledger floors, ladder hysteresis,
breaker state machine, retry budget, loadgen determinism, BUSY retry
hints) is unit-level with no engines, so the heavy device work stays in
exactly two tests.
"""

import dataclasses
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maggy_tpu import telemetry
from maggy_tpu.models import Decoder, DecoderConfig
from maggy_tpu.models.generate import generate_cached
from maggy_tpu.parallel.sharding import unbox
from maggy_tpu.resilience import chaos
from maggy_tpu.serve import (
    Burst,
    SamplingParams,
    ServeClient,
    TenantMix,
    TrafficReplay,
    TrafficSpec,
)
from maggy_tpu.serve.fleet import ReplicaSpec, Router, RouterConfig, launch_fleet
from maggy_tpu.serve.fleet.replica import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    RetryBudget,
)
from maggy_tpu.serve.fleet.router import BrownoutLadder
from maggy_tpu.serve.loadgen import generate, summarize
from maggy_tpu.serve.qos import (
    BEST_EFFORT,
    PREMIUM,
    STANDARD,
    QosQueue,
    QuotaLedger,
    validate_qos,
)

CFG = DecoderConfig.tiny(max_seq_len=64, dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = Decoder(CFG)
    return unbox(
        model.init(jax.random.key(7), jnp.zeros((1, 8), jnp.int32))["params"]
    )


def reference(params, prompt, max_new):
    decode_model = Decoder(dataclasses.replace(CFG, decode=True))
    buf = np.zeros((1, len(prompt) + max_new), np.int32)
    buf[0, : len(prompt)] = prompt
    out = generate_cached(
        decode_model, params, jnp.asarray(buf), jnp.asarray([len(prompt)])
    )
    return list(np.asarray(out)[0, len(prompt):])


def _req(qos):
    return types.SimpleNamespace(qos=qos)


# ------------------------------------------------------------------ qos units


def test_qos_queue_priority_order_and_requeue_front():
    q = QosQueue()
    be1, be2 = _req(BEST_EFFORT), _req(BEST_EFFORT)
    pr, st = _req(PREMIUM), _req(STANDARD)
    for r in (be1, be2, pr, st):
        q.append(r)
    assert len(q) == 4
    assert q.depths() == {PREMIUM: 1, STANDARD: 1, BEST_EFFORT: 2}
    # highest class first, FIFO within a class
    assert q.pop_next()[0] is pr
    assert q.pop_next()[0] is st
    assert q.pop_next()[0] is be1
    assert q.pop_next()[0] is be2
    # a preempted request outranks fresh arrivals of its own class only
    q.append(be1)
    q.requeue_front(be2)
    pr2 = _req(PREMIUM)
    q.append(pr2)
    assert q.pop_next()[0] is pr2, "requeue must not jump classes"
    assert q.pop_next()[0] is be2, "requeue goes to the front of its class"
    assert q.pop_next()[0] is be1
    assert q.pop_next() == (None, [])


def test_validate_qos_default_and_unknown():
    assert validate_qos(None) == BEST_EFFORT
    assert validate_qos("") == BEST_EFFORT
    assert validate_qos("premium") == PREMIUM
    with pytest.raises(ValueError, match="unknown qos"):
        validate_qos("platinum")


def test_quota_ledger_floor_in_both_directions():
    t0 = 1000.0
    led = QuotaLedger(window_s=10.0, min_tokens=32)
    # below min_tokens the ledger abstains entirely
    led.charge(BEST_EFFORT, 10, t0)
    assert not led.over_share(BEST_EFFORT, t0)
    # a best-effort flood is over its 1/12 share -> deferrable
    led.charge(BEST_EFFORT, 90, t0)
    assert led.over_share(BEST_EFFORT, t0)
    assert not led.over_share(PREMIUM, t0)
    # the floor cuts the other way too: an all-premium window defers
    # premium while best-effort waits under share
    led2 = QuotaLedger(window_s=10.0, min_tokens=32)
    led2.charge(PREMIUM, 100, t0)
    assert led2.over_share(PREMIUM, t0)
    assert not led2.over_share(BEST_EFFORT, t0)
    # charges age out of the sliding window
    assert led2.totals(t0 + 30.0)[PREMIUM] == 0
    assert not led2.over_share(PREMIUM, t0 + 30.0)
    snap = led.snapshot(t0)
    assert snap["tokens"][BEST_EFFORT] == 100
    assert snap["shares"][BEST_EFFORT] == 1.0


def test_pop_next_quota_deferral_is_work_conserving():
    t0 = 2000.0
    led = QuotaLedger(window_s=10.0, min_tokens=32)
    led.charge(PREMIUM, 100, t0)  # premium over share
    q = QosQueue()
    pr, be = _req(PREMIUM), _req(BEST_EFFORT)
    q.append(pr)
    q.append(be)
    # premium over share AND best-effort waiting under share -> defer
    picked, deferred = q.pop_next(led, t0)
    assert picked is be
    assert deferred == [PREMIUM]
    # premium alone: quotas never idle a slot
    picked, deferred = q.pop_next(led, t0)
    assert picked is pr and deferred == []
    # every waiting class over share -> plain priority
    led3 = QuotaLedger(window_s=10.0, min_tokens=32)
    led3.charge(PREMIUM, 50, t0)
    led3.charge(BEST_EFFORT, 50, t0)  # both above their fractions
    q.append(be)
    q.append(pr)
    picked, deferred = q.pop_next(led3, t0)
    assert picked is pr and deferred == []


# -------------------------------------------------------------- ladder units


def test_brownout_ladder_hysteresis():
    lad = BrownoutLadder(escalate_s=1.0, recover_s=2.0)
    assert lad.step(True, 0.0) == (0, None)  # burn starts, no step yet
    assert lad.step(True, 0.5) == (0, None)
    assert lad.step(True, 1.0) == (1, "escalated")
    assert lad.step(True, 1.5) == (1, None)  # one step per escalate_s
    assert lad.step(True, 2.0) == (2, "escalated")
    assert lad.step(True, 3.0) == (3, "escalated")
    assert lad.step(True, 9.0) == (3, None)  # clamped at shed
    # recovery needs recover_s of CLEAN burn; a blip resets the clock
    assert lad.step(False, 10.0) == (3, None)
    assert lad.step(True, 11.0) == (3, None)
    assert lad.step(False, 11.5) == (3, None)
    assert lad.step(False, 13.5) == (2, "recovered")
    assert lad.step(False, 15.5) == (1, "recovered")
    assert lad.step(False, 17.5) == (0, "recovered")
    snap = lad.snapshot()
    assert snap["level"] == 0 and snap["name"] == "normal"
    assert [lvl for _, lvl in snap["history"]] == [1, 2, 3, 2, 1, 0]


# ------------------------------------------------------------- breaker units


def test_circuit_breaker_state_machine():
    b = CircuitBreaker(1, trips=2, cooldown_s=5.0)
    t0 = 100.0
    # one outlier score is not a trip
    assert b.score(500.0, 50.0, ratio=3.0, min_ms=50.0, now=t0) is None
    assert b.state == BREAKER_CLOSED and b.ok(t0)
    # the second consecutive outlier opens
    assert b.score(500.0, 50.0, ratio=3.0, min_ms=50.0, now=t0 + 1) == "opened"
    assert b.state == BREAKER_OPEN
    assert not b.ok(t0 + 2)
    # cooldown elapses -> half-open, one probation probe at a time
    assert b.ok(t0 + 6.5)
    assert b.state == BREAKER_HALF_OPEN
    assert b.take_probe("p1")
    assert not b.ok(t0 + 6.6), "second dispatch must wait out the probe"
    assert not b.take_probe("p2")
    # only the probe's own rid renders the verdict
    assert b.observe_ttft("stale-slow-stream", 900.0, t0 + 7) is None
    assert b.state == BREAKER_HALF_OPEN
    # fast probe closes (close_below = ratio * peer = 150ms)
    assert b.observe_ttft("p1", 60.0, t0 + 7) == "closed"
    assert b.state == BREAKER_CLOSED
    # re-trip, then a SLOW probe re-opens and restarts the cooldown
    b.score(500.0, 50.0, ratio=3.0, min_ms=50.0, now=t0 + 8)
    assert b.score(500.0, 50.0, ratio=3.0, min_ms=50.0, now=t0 + 9) == "opened"
    assert b.ok(t0 + 15)
    assert b.take_probe("p3")
    assert b.observe_ttft("p3", 400.0, t0 + 15) == "reopened"
    assert not b.ok(t0 + 16)
    assert b.snapshot()["opened_total"] == 2
    # a lost probe (replica died mid-probation) frees the slot
    assert b.ok(t0 + 21)
    assert b.take_probe("p4")
    b.probe_lost("p4")
    assert b.take_probe("p5")


def test_retry_budget_defers_requeue_storms():
    rb = RetryBudget(capacity=2, window_s=1.0)
    t0 = 50.0
    assert rb.consume(t0)
    assert rb.consume(t0)
    assert not rb.consume(t0), "dry bucket defers the third requeue"
    # the bucket refills at capacity/window
    assert rb.consume(t0 + 1.0)


# ------------------------------------------------------------- loadgen units


def _spec(seed=7, **kw):
    base = dict(
        seed=seed,
        duration_s=20.0,
        base_rps=6.0,
        tenants=(
            TenantMix("acme", qos=PREMIUM, weight=1.0, prompt_len=10,
                      prefix_len=4, n_prefixes=2, max_new=4),
            TenantMix("bulk", qos=BEST_EFFORT, weight=3.0, prompt_len=8),
        ),
    )
    base.update(kw)
    return TrafficSpec(**base)


def test_loadgen_deterministic_and_shaped():
    a = generate(_spec())
    b = generate(_spec())
    assert a == b, "same spec + seed must replay byte-identically"
    assert a != generate(_spec(seed=8))
    # time-sorted, seq-stamped, prompt shapes per mix
    assert [x.seq for x in a] == list(range(len(a)))
    assert all(a[i].at_s <= a[i + 1].at_s for i in range(len(a) - 1))
    acme = [x for x in a if x.tenant == "acme"]
    bulk = [x for x in a if x.tenant == "bulk"]
    assert acme and bulk
    assert all(x.qos == PREMIUM and len(x.prompt) == 10 for x in acme)
    assert all(x.qos == BEST_EFFORT and len(x.prompt) == 8 for x in bulk)
    # weights steer the split (3:1 within Poisson noise)
    assert len(bulk) > len(acme)
    # shared-prefix population: acme prompts reuse <= n_prefixes stems
    stems = {x.prompt[:4] for x in acme}
    assert 1 <= len(stems) <= 2
    # a burst multiplies offered load inside its window
    burst = generate(_spec(bursts=(Burst(start_s=5.0, duration_s=5.0, mult=5.0),)))
    in_window = [x for x in burst if 5.0 <= x.at_s < 10.0]
    outside = [x for x in burst if 10.0 <= x.at_s < 15.0]
    assert len(in_window) > 2 * max(1, len(outside))
    # validation kills malformed specs at build time
    with pytest.raises(ValueError, match="unknown qos"):
        generate(_spec(tenants=(TenantMix("x", qos="gold"),)))
    with pytest.raises(ValueError, match="prefix_len"):
        generate(_spec(tenants=(TenantMix("x", prompt_len=4, prefix_len=8),)))


def test_loadgen_tenant_burst_chaos_seam():
    baseline = generate(_spec())
    chaos.install(chaos.Chaos.parse("tenant_burst:tenant=bulk,mult=4"))
    try:
        flooded = generate(_spec())
    finally:
        chaos.install(None)
    base_bulk = [x for x in baseline if x.tenant == "bulk"]
    hot_bulk = [x for x in flooded if x.tenant == "bulk"]
    assert len(hot_bulk) > 2 * len(base_bulk)
    # the other tenant's private PRNG stream is untouched by the chaos
    strip = lambda xs: [(x.at_s, x.prompt) for x in xs if x.tenant == "acme"]
    assert strip(flooded) == strip(baseline)


# --------------------------------------------------------- router BUSY units


def _fake_replica(index, num_slots=2):
    return types.SimpleNamespace(
        index=index,
        state="up",
        spec=types.SimpleNamespace(num_slots=num_slots),
        describe=lambda: {"replica": index, "state": "up", "addr": None,
                          "restarts": 0, "devices": [], "uptime_s": 0.0},
        client=None,
    )


def test_busy_carries_retry_after_ms_and_brownout_sheds_best_effort_only():
    router = Router([_fake_replica(0)], config=RouterConfig())
    router._stats_cache[0] = {"num_slots": 2, "active_slots": 0,
                              "queue_depth": 0, "ttft_ms_p50": 10.0}
    # force the ladder to shed (level 3) the way the pump would
    router.brownout.step(True, 0.0)
    for t in (3.0, 6.0, 9.0):
        router.brownout.step(True, t)
    assert router.brownout.level() == 3
    reply = router._on_submit({"prompt": [1, 2]})  # default qos: best_effort
    assert reply["type"] == "BUSY"
    assert reply["retry_after_ms"] >= 100.0
    assert reply["retry_after_s"] == pytest.approx(
        reply["retry_after_ms"] / 1e3, abs=1e-3
    )
    # consecutive sheds stagger their hints so retries don't resynchronize
    hints = {router._on_submit({"prompt": [1]})["retry_after_ms"]
             for _ in range(6)}
    assert len(hints) > 1
    # premium admission is untouched at every brownout level
    ok = router._on_submit({"prompt": [1, 2], "qos": PREMIUM,
                            "tenant": "acme"})
    assert ok["type"] == "SUBMIT"
    with pytest.raises(ValueError, match="unknown qos"):
        router._on_submit({"prompt": [1], "qos": "gold"})


def test_dispatch_holds_best_effort_but_not_premium_under_slo_pressure():
    """The SLO queue-hold is class-aware: an over-budget projection parks
    fresh best-effort while premium behind it still dispatches."""
    router = Router(
        [_fake_replica(0)],
        config=RouterConfig(slo_ttft_ms=150.0, admission="queue"),
    )
    router._stats_cache[0] = {"num_slots": 2, "active_slots": 2,
                              "queue_depth": 10, "ttft_ms_p50": 100.0}
    be = router._on_submit({"prompt": [1, 2]})["id"]
    pr = router._on_submit({"prompt": [3, 4], "qos": PREMIUM})["id"]
    sent = []
    router.replicas[0].client = types.SimpleNamespace(
        submit=lambda **kw: sent.append(kw) or f"remote-{len(sent)}"
    )
    router._dispatch_pending(time.time())
    router._dispatch_pending(time.time())
    assert [kw["prompt"] for kw in sent] == [[3, 4]], (
        "premium dispatches past the hold; best-effort parks"
    )
    assert router._on_poll({"id": be})["state"] == "queued"
    assert router._entries[pr].state == "routed"


# -------------------------------------------- scheduler priority (engine)


def test_priority_preemption_byte_parity(params):
    """Page pressure preempts the LOWEST class first, and a premium arrival
    never loses its pages to best-effort growth — while every stream stays
    byte-identical to an unpressured run (the PR-10 resume seam)."""
    from maggy_tpu.serve import Engine, Scheduler

    # geometry from test_paged_kv: 14-token prompts fit one page, max_new=12
    # grows each row to 2 pages mid-decode; 3 rows x 2 pages > 5 usable
    jobs = [
        (list(range(1 + i, 15 + i)),
         SamplingParams(max_new=12, temperature=0.7, seed=i))
        for i in range(3)
    ]
    tel = telemetry.Telemetry(worker="qos-preempt-test")
    engine = Engine(
        CFG, params, num_slots=3, paged=True, num_pages=6,
        telemetry_recorder=tel,
    )
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        reqs = [
            scheduler.submit(p, sp, tenant="bulk", qos=BEST_EFFORT)
            for p, sp in jobs[:2]
        ]
        reqs.append(
            scheduler.submit(jobs[2][0], jobs[2][1], tenant="acme",
                             qos=PREMIUM)
        )
        deadline = time.time() + 90
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in reqs
        ):
            time.sleep(0.01)
        assert all(r.state == "done" for r in reqs), [
            (r.state, r.error) for r in reqs
        ]
        streams = [list(r.tokens) for r in reqs]
        preemptions = scheduler.preemptions
        counters = {c: dict(v) for c, v in scheduler.qos_counters.items()}
    finally:
        scheduler.stop()
    assert preemptions >= 1, "pressure did not preempt"
    # victims were best-effort; the premium stream kept its pages
    assert counters[BEST_EFFORT]["preempted"] == preemptions
    assert counters[PREMIUM]["preempted"] == 0
    # byte parity vs an unpressured run of the same jobs
    engine2 = Engine(CFG, params, num_slots=3, paged=True, num_pages=12)
    sched2 = Scheduler(engine2)
    sched2.start()
    try:
        free_reqs = [sched2.submit(p, sp) for p, sp in jobs]
        deadline = time.time() + 90
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in free_reqs
        ):
            time.sleep(0.01)
        assert all(r.state == "done" for r in free_reqs)
        free = [list(r.tokens) for r in free_reqs]
    finally:
        sched2.stop()
    assert streams == free, "priority preemption changed token streams"
    # observability: per-class counters and the priority event both fired
    snap = tel.snapshot()
    assert snap["counters"].get(
        f"serve.qos.preempted.{BEST_EFFORT}"
    ) == preemptions
    names = [e["name"] for e in tel.drain_events()]
    assert "req.preempted_for_priority" in names


def test_quota_starvation_regression(params):
    """A best-effort flood cannot park a premium arrival: priority
    admission pops it past the whole flood as soon as a slot frees."""
    from maggy_tpu.serve import Engine, Scheduler

    engine = Engine(CFG, params, num_slots=1)
    scheduler = Scheduler(engine)
    scheduler.start()
    try:
        flood = [
            scheduler.submit([10 + i, 11, 12], SamplingParams(max_new=6),
                             tenant="bulk")
            for i in range(10)
        ]
        premium = scheduler.submit(
            [1, 2, 3], SamplingParams(max_new=6), tenant="acme", qos=PREMIUM
        )
        deadline = time.time() + 120
        reqs = flood + [premium]
        while time.time() < deadline and any(
            r.state not in ("done", "failed") for r in reqs
        ):
            time.sleep(0.01)
        assert all(r.state == "done" for r in reqs)
        # premium (submitted LAST) was admitted ahead of most of the flood
        later = [
            r for r in flood
            if r.admitted_ts is not None
            and r.admitted_ts > premium.admitted_ts
        ]
        assert len(later) >= 5, (
            f"premium only outran {len(later)} of 10 best-effort arrivals"
        )
        qstats = scheduler.stats()["qos"]
        assert qstats["counters"][PREMIUM]["admitted"] == 1
    finally:
        scheduler.stop()


# ------------------------------------------------------ acceptance (fleet)


@pytest.mark.slow  # heavy-compile: warms every storm shape before the replay
def test_overload_replay_acceptance(params):
    """ACCEPTANCE (overload): a seeded 2-class replay at ~2x capacity walks
    the brownout ladder through every level, premium attains its TTFT SLO
    >= 95% with completed streams byte-identical to an unloaded decode, and
    best-effort degrades (clamp -> queue -> shed) instead of cliffing."""
    tel = telemetry.Telemetry(worker="overload-test")
    router = launch_fleet(
        ReplicaSpec(CFG, params, num_slots=3, paged=True, num_pages=6),
        replicas=2,
        telemetry_recorder=tel,
        config=RouterConfig(
            slo_ttft_ms=1000.0,
            admission="queue",
            brownout_escalate_s=0.3,
            brownout_recover_s=1.0,
        ),
    )
    host, port = router.start(host="127.0.0.1")
    # premium prompts ARE the 3 stems (prefix_len == prompt_len), so the
    # unloaded byte-parity reference is 3 cached decodes, not one per request
    spec = TrafficSpec(
        seed=11,
        duration_s=8.0,
        base_rps=60.0,
        tenants=(
            TenantMix("acme", qos=PREMIUM, weight=1.0, prompt_len=14,
                      prefix_len=14, n_prefixes=3, max_new=6),
            TenantMix("bulk", qos=BEST_EFFORT, weight=11.0, prompt_len=14,
                      max_new=16),
        ),
        bursts=(Burst(start_s=1.0, duration_s=3.0, mult=2.0),),
    )
    schedule = generate(spec)
    assert len(schedule) > 30, "the storm must actually be a storm"
    try:
        with ServeClient((host, port), router.secret) as warm:
            # absorb both replicas' XLA compiles at every shape the storm
            # will hit — fresh 14-token prefills, the longer resume-prefill
            # bucket a preempted request re-enters through, batched decode
            # (concurrent submits fill all slots), and the prefix-hit admit
            # path — before the overload clock starts. A first-use compile
            # mid-storm stalls the replica loop for seconds and charges the
            # stall to whatever premium is queued behind it. Standard
            # class: the ladder never sheds it, and warmup TTFTs must not
            # pollute the premium attainment under test.
            for i in range(4):
                warm.generate(list(range(1 + i, 15 + i)), max_new=2,
                              qos=STANDARD, timeout=240)
            stem = list(range(40, 54))
            for _ in range(2):  # second pass admits via the prefix cache
                warm.generate(stem, max_new=2, qos=STANDARD, timeout=240)
            rids = [
                warm.submit(list(range(2 + i, 26 + i)), max_new=4,
                            qos=STANDARD)
                for i in range(8)
            ]
            for rid in rids:
                warm.result(rid, timeout=240)
        # the compile TTFTs blew the SLO and lit the burn alert: let the
        # ladder walk back to normal before the measured storm begins
        deadline = time.time() + 90
        while time.time() < deadline and (
            router.brownout.level() != 0
            or any(
                a.get("alert") == "alert.ttft_slo_burn"
                for a in router.alerts.firing()
            )
        ):
            time.sleep(0.2)
        assert router.brownout.level() == 0, "warmup burn never cleared"
        hist_mark = len(router.brownout.snapshot()["history"])
        shed_mark = router.counters["shed"]
        preempt_mark = sum(
            r.server.scheduler.preemptions
            for r in router.replicas
            if r.server is not None
        )
        with ServeClient((host, port), router.secret) as client:
            replay = TrafficReplay(client, schedule, result_timeout_s=25.0)
            outcomes = replay.run(timeout=180.0)
            stats = client.stats()
        ladder = router.brownout.snapshot()
        ladder["history"] = ladder["history"][hist_mark:]
        preemptions = sum(
            r.server.scheduler.preemptions
            for r in router.replicas
            if r.server is not None
        )
    finally:
        router.stop()
    by_class = summarize(outcomes)
    # every ladder level was visible on the way down the brownout
    seen_levels = {lvl for _, lvl in ladder["history"]}
    assert {1, 2, 3} <= seen_levels, ladder
    assert stats["routing"]["shed"] > shed_mark, (
        "level 3 never shed best-effort"
    )
    assert preemptions > preempt_mark, (
        "2x overload never pressured the page pool"
    )
    # premium held its SLO through the storm
    slo = stats["slo_by_class"][PREMIUM]
    attained = slo["ok"] / max(1, slo["ok"] + slo["miss"])
    assert attained >= 0.95, (slo, by_class)
    prem = by_class[PREMIUM]
    assert prem["done"] >= 1 and prem["shed"] == 0
    # byte parity: every completed premium stream matches the unloaded
    # single-engine decode of its stem
    refs = {}
    checked = 0
    for o in outcomes:
        if o["qos"] != PREMIUM or o["status"] != "done":
            continue
        prompt = schedule[o["seq"]].prompt
        if prompt not in refs:
            refs[prompt] = reference(params, list(prompt), 6)
        got = list(o["snapshot"]["tokens"])
        assert got == refs[prompt], (
            f"premium seq {o['seq']} diverged under overload"
        )
        checked += 1
    assert checked >= 1
    # the brownout threshold alert fired off the gauge (entry + exit events)
    alert_events = [
        e for e in tel.drain_events()
        if e["name"] in ("alert.firing", "alert.resolved")
        and e.get("attrs", {}).get("alert") == "alert.brownout"
    ]
    assert any(e["name"] == "alert.firing" for e in alert_events), (
        "fleet.brownout_level > 0 never raised alert.brownout"
    )


@pytest.mark.slow  # two fleet launches + breaker cooldown/probe wall-clock
def test_gray_failure_breaker_acceptance(params):
    """ACCEPTANCE (gray failure): ``replica_slow`` chaos on one of two
    replicas opens its breaker, dispatch drains to the healthy peer with
    zero failed requests, and a half-open probe closes it after the chaos
    clears."""
    tel = telemetry.Telemetry(worker="gray-test")
    router = launch_fleet(
        ReplicaSpec(CFG, params, num_slots=2),
        replicas=2,
        telemetry_recorder=tel,
        config=RouterConfig(
            breaker_trips=2,
            breaker_cooldown_s=1.0,
            breaker_window_s=8.0,
        ),
    )
    host, port = router.start(host="127.0.0.1")
    chaos.install(
        chaos.Chaos.parse("replica_slow:replica=1,ms=300,times=100000")
    )
    try:
        with ServeClient((host, port), router.secret) as client:
            # warm both replicas' compiles before the breaker clock matters
            for _ in range(4):
                client.generate([5, 6, 7], max_new=2, timeout=240)
            # concurrent bursts: the healthy replica alone projects worse
            # than the gray one's 300ms handicap, so dispatch keeps feeding
            # replica 1 fresh (slow) TTFT samples until its p95 detaches
            deadline = time.time() + 90
            while (
                router.breakers[1].state == BREAKER_CLOSED
                and time.time() < deadline
            ):
                rids = [
                    client.submit([8 + i, 9, 10, 11], max_new=2)
                    for i in range(16)
                ]
                for rid in rids:
                    client.result(rid, timeout=240)
            assert router.breakers[1].state != BREAKER_CLOSED, (
                "gray replica never tripped its breaker"
            )
            # with the breaker open, dispatch drains to the healthy peer
            routed_to = []
            for i in range(6):
                rid = client.submit([20 + i, 21, 22], max_new=2)
                snap = client.result(rid, timeout=240)
                routed_to.append(snap["replica"])
            assert set(routed_to) == {0}, routed_to
            # chaos clears; a half-open probation probe closes the breaker.
            # marginal probes may re-open it (close_below is tight on an
            # idle CPU fleet) — keep offering probes until one lands
            chaos.install(None)
            deadline = time.time() + 90
            while (
                router.breakers[1].state != BREAKER_CLOSED
                and time.time() < deadline
            ):
                client.generate([30, 31, 32], max_new=2, timeout=240)
                time.sleep(0.05)
            assert router.breakers[1].state == BREAKER_CLOSED, (
                router.breakers[1].snapshot()
            )
            stats = client.stats()
    finally:
        chaos.install(None)
        router.stop()
    # the whole episode failed nothing and the transitions were counted
    assert stats["routing"]["failed"] == 0
    assert stats["breakers"]["1"]["opened_total"] >= 1
    counters = tel.snapshot().get("counters", {})
    assert counters.get("fleet.breaker_opened", 0) >= 1
    assert counters.get("fleet.breaker_closed", 0) >= 1
