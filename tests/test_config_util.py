"""Config validation + util (kwarg injection, return-val handling, ShardingSpec)."""

import os

import pytest

from maggy_tpu import Searchspace, exceptions, util
from maggy_tpu.config import (
    AblationConfig,
    BaseConfig,
    DistributedConfig,
    HyperparameterOptConfig,
)
from maggy_tpu.parallel import ShardingSpec


def sp():
    return Searchspace(lr=("DOUBLE", [0.0, 1.0]))


def test_hpo_config_validation():
    cfg = HyperparameterOptConfig(num_trials=4, optimizer="randomsearch", searchspace=sp())
    assert cfg.direction == "max"
    with pytest.raises(ValueError):
        HyperparameterOptConfig(num_trials=0, optimizer="randomsearch", searchspace=sp())
    with pytest.raises(ValueError):
        HyperparameterOptConfig(
            num_trials=2, optimizer="randomsearch", searchspace=sp(), direction="up"
        )
    with pytest.raises(TypeError):
        HyperparameterOptConfig(num_trials=2, optimizer="randomsearch", searchspace={})


def test_distributed_config_zero_shim():
    cfg = DistributedConfig(module=object, zero_lvl=3)
    assert cfg.sharding == "fsdp"
    cfg = DistributedConfig(module=object, zero_lvl=0)
    assert cfg.sharding == "dp"
    with pytest.raises(ValueError):
        DistributedConfig(module=object, zero_lvl=5)
    spec = cfg.resolve_sharding(8)
    assert spec.dp == 8 and spec.num_devices == 8


def test_sharding_spec():
    s = ShardingSpec(dp=2, fsdp=2, tp=2)
    assert s.num_devices == 8
    assert ShardingSpec.preset("fsdp", 8) == ShardingSpec(fsdp=8)
    two_d = ShardingSpec.preset("2d", 8)
    assert two_d.fsdp * two_d.tp == 8 and two_d.tp == 2
    with pytest.raises(ValueError):
        ShardingSpec(dp=0)
    assert ShardingSpec(fsdp=4).scaled_to(8) == ShardingSpec(dp=2, fsdp=4)
    with pytest.raises(ValueError):
        ShardingSpec(fsdp=3).scaled_to(8)


def test_inject_kwargs():
    def fn_a(hparams, reporter):
        return hparams, reporter

    def fn_b():
        return None

    def fn_c(**kwargs):
        return kwargs

    avail = {"hparams": {"x": 1}, "reporter": "R", "model": "M"}
    assert util.inject_kwargs(fn_a, avail) == {"hparams": {"x": 1}, "reporter": "R"}
    assert util.inject_kwargs(fn_b, avail) == {}
    assert util.inject_kwargs(fn_c, avail) == avail


def test_inject_kwargs_unknown_param_errors():
    def fn_bad(hparams, my_dataset):
        return None

    def fn_ok(hparams, my_dataset="default"):
        return None

    avail = {"hparams": {}, "reporter": "R"}
    with pytest.raises(exceptions.BadArgumentsError, match="my_dataset"):
        util.inject_kwargs(fn_bad, avail)
    # defaults are fine — the framework just doesn't fill them
    assert util.inject_kwargs(fn_ok, avail) == {"hparams": {}}

    # **kwargs does not bypass the required-param check
    def fn_kw(hparams, my_dataset, **kw):
        return None

    with pytest.raises(exceptions.BadArgumentsError, match="my_dataset"):
        util.inject_kwargs(fn_kw, avail)

    # positional-only params are uninjectable, even with matching names
    exec("def fn_pos(hparams, /): return None", globals())
    with pytest.raises(exceptions.BadArgumentsError, match="positional-only"):
        util.inject_kwargs(globals()["fn_pos"], avail)


def test_lagom_arg_validation(tmp_env):
    from maggy_tpu import experiment

    cfg = HyperparameterOptConfig(
        num_trials=1, optimizer="randomsearch", searchspace=sp(), es_policy="none"
    )
    with pytest.raises(TypeError, match="swapped"):
        experiment.lagom(cfg, lambda hparams: 1.0)
    with pytest.raises(TypeError, match="callable"):
        experiment.lagom("not-a-function", cfg)


def test_handle_return_val(tmp_path):
    d = str(tmp_path / "trial")
    assert util.handle_return_val(0.5, d, "metric") == 0.5
    assert os.path.exists(os.path.join(d, ".metric"))
    assert util.handle_return_val({"metric": 2, "loss": 0.1}, d, "metric") == 2.0
    with pytest.raises(exceptions.ReturnTypeError):
        util.handle_return_val(None, d, "metric")
    with pytest.raises(exceptions.ReturnTypeError):
        util.handle_return_val({"loss": 0.1}, d, "metric")
    with pytest.raises(exceptions.MetricTypeError):
        util.handle_return_val({"metric": "bad"}, d, "metric")


def test_base_and_ablation_config():
    c = BaseConfig(hparams={"a": 1})
    assert c.hparams == {"a": 1}
    a = AblationConfig(ablation_study=object())
    assert a.ablator == "loco"
    with pytest.raises(ValueError):
        AblationConfig(ablation_study=object(), direction="sideways")
