"""Control-plane stress: many near-zero-cost trials, max concurrency, mixed
early stops and flaky errors, tiny heartbeat interval — shakes out scheduling
races (the double-execution and misattribution races fixed during development
were exactly this shape). SURVEY §5.2: the reference has no race detection;
this adversarial load is the substitute."""

import threading

import pytest

from maggy_tpu import Searchspace, experiment
from maggy_tpu.config import HyperparameterOptConfig

pytestmark = pytest.mark.slow  # subprocess/multi-process tier


def test_hpo_stress_no_lost_or_duplicated_trials(tmp_env):
    ran = []
    ran_lock = threading.Lock()

    def train(hparams, reporter):
        with ran_lock:
            ran.append(round(hparams["x"], 9))
        for step in range(3):
            reporter.broadcast(hparams["x"] + step * 1e-3, step=step)
        if hparams["x"] > 0.95:  # a few flaky trials
            raise ValueError("flaky")
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=64,
        optimizer="randomsearch",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0]), y=("DOUBLE", [0.0, 1.0])),
        direction="max",
        num_executors=8,
        es_policy="median",
        es_interval=0,
        es_min=5,
        hb_interval=0.01,
        seed=9,
    )
    result = experiment.lagom(train, cfg)
    # every trial ran exactly once: no duplicates, no losses
    assert result["num_trials"] == 64
    assert len(ran) == 64, f"{len(ran)} executions for 64 trials"
    assert len(set(ran)) == 64, "a trial executed twice"
    assert result["errors"] >= 1  # the flaky band above 0.95 fired
    assert result["best"]["metric"] <= 0.95  # errored trials never win


def test_asha_stress_budget_accounting(tmp_env):
    """ASHA under max concurrency: rung arithmetic must hold exactly."""
    budgets = []
    lock = threading.Lock()

    def train(hparams, budget, reporter):
        with lock:
            budgets.append(int(budget))
        reporter.broadcast(hparams["x"], step=0)
        return hparams["x"]

    cfg = HyperparameterOptConfig(
        num_trials=32,
        optimizer="asha",
        searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
        direction="max",
        num_executors=8,
        es_policy="none",
        hb_interval=0.01,
        seed=4,
    )
    result = experiment.lagom(train, cfg)
    assert budgets.count(1) == 32
    assert budgets.count(2) == 16
    assert budgets.count(4) == 8
    assert result["num_trials"] == 56


def test_asha_256_trials_scale(tmp_env):
    """BASELINE config-2 shape at control-plane scale: 256 ASHA trials with a
    small REAL train step (jitted ridge-regression GD, compiled once) through
    the full driver/RPC/executor path. Asserts completion without deadlock,
    no leaked executor/heartbeat threads, and monotone trial completion
    (VERDICT r1 item 9). Runs in well under 3 minutes on the CI CPU mesh."""
    import time

    import jax
    import jax.numpy as jnp

    @jax.jit
    def gd_steps(w, X, y, lr, n):
        def body(_, w):
            grad = X.T @ (X @ w - y) / X.shape[0]
            return w - lr * grad

        return jax.lax.fori_loop(0, n, body, w)

    X = jnp.array([[1.0, 0.5], [0.3, 2.0], [1.5, 1.0], [0.2, 0.8]])
    y = jnp.array([1.0, 2.0, 1.8, 0.9])

    completions = []  # budget of each trial, in completion order
    lock = threading.Lock()

    def train(hparams, budget, reporter):
        w = gd_steps(jnp.zeros(2), X, y, hparams["lr"], 4 * int(budget))
        loss = float(jnp.mean((X @ w - y) ** 2))
        reporter.broadcast(-loss, step=0)
        with lock:
            completions.append(int(budget))
        return -loss

    before_threads = threading.active_count()
    cfg = HyperparameterOptConfig(
        num_trials=256,
        optimizer="asha",
        searchspace=Searchspace(lr=("DOUBLE", [0.001, 0.4])),
        direction="max",
        num_executors=8,
        es_policy="none",
        hb_interval=0.01,
        seed=11,
    )
    t0 = time.monotonic()
    result = experiment.lagom(train, cfg)
    wall = time.monotonic() - t0
    assert wall < 180, f"256-trial ASHA took {wall:.1f}s"

    # rung arithmetic at reduction factor 2: 256 + 128 + 64 + 32 + 16 + ...
    assert result["num_trials"] >= 256
    assert len(completions) == result["num_trials"]
    # ASHA promotion ordering: a rung-(r+1) trial is only *suggested* after
    # reduction_factor times as many rung-r trials have finished, so at every
    # prefix of the completion sequence n_r >= 2 * n_{r+1} must hold
    budgets_seen = sorted(set(completions))
    counts = {bgt: 0 for bgt in budgets_seen}
    for bgt in completions:
        counts[bgt] += 1
        for lo, hi in zip(budgets_seen, budgets_seen[1:]):
            assert counts[lo] >= 2 * counts[hi], (
                f"rung inversion: {counts[lo]}x budget-{lo} vs "
                f"{counts[hi]}x budget-{hi}"
            )
    # all executor worker + heartbeat threads joined (small slack for the
    # daemonized asyncio server thread shared across experiments)
    time.sleep(0.5)
    assert threading.active_count() <= before_threads + 2, (
        f"{threading.active_count() - before_threads} leaked threads"
    )
    assert result["best"]["metric"] <= 0.0
