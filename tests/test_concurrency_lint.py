"""Concurrency lint suite: the lock-discipline analyzer
(tools/check_concurrency.py), the unified runner (tools/check_all.py), and
the runtime lock-order assertion (core/lockdebug.py).

Mirrors the shape of the other lint gates (test_prefetch.py's host-sync
block, test_telemetry.py's name/docs lints): synthetic violation + annotated
clean fixture per check, a whole-tree clean-run gate, and a
required-annotation-removal failure.
"""
import os
import textwrap
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    import importlib.util
    import sys

    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(tools, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses resolve string annotations here
    spec.loader.exec_module(mod)
    return mod


def _lint():
    return _load("check_concurrency")


# ------------------------------------------------- check 1: unguarded state


UNGUARDED = textwrap.dedent(
    """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def _loop(self):
            while True:
                self.n += 1

        def read(self):
            return self.n
    """
)


def test_unguarded_shared_state_flagged():
    hits = _lint().find_violations(UNGUARDED, "<bad>")
    assert hits, "thread-written attr read without the lock must be flagged"
    assert any("Counter.n" in what for _, what in hits), hits


def test_guarded_sites_clean():
    ok = textwrap.dedent(
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def _loop(self):
                while True:
                    with self._lock:
                        self.n += 1

            def read(self):
                with self._lock:
                    return self.n
        """
    )
    assert _lint().find_violations(ok, "<ok>") == []


def test_guarded_by_declaration_trusted():
    ok = UNGUARDED.replace(
        "self.n = 0", "self.n = 0  # guarded-by: gil-atomic-int"
    )
    assert _lint().find_violations(ok, "<decl>") == []


def test_race_ok_needs_a_reason():
    justified = UNGUARDED.replace(
        "self.n = 0", "self.n = 0  # race: ok — monotonic counter, torn reads benign"
    )
    assert _lint().find_violations(justified, "<why>") == []

    bare = UNGUARDED.replace("self.n = 0", "self.n = 0  # race: ok")
    hits = _lint().find_violations(bare, "<bare>")
    assert any("without a reason" in what for _, what in hits), hits


def test_def_line_guard_covers_helper_methods():
    ok = textwrap.dedent(
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def _loop(self):
                with self._lock:
                    self._bump()

            def _bump(self):  # guarded-by: _lock
                self.n += 1

            def read(self):
                with self._lock:
                    return self.n
        """
    )
    assert _lint().find_violations(ok, "<helper>") == []


# --------------------------------------------------- check 2: lock ordering


CYCLE = textwrap.dedent(
    """
    import threading

    class A:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()

        def fwd(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def rev(self):
            with self._b_lock:
                with self._a_lock:
                    pass
    """
)


def test_lock_order_cycle_flagged():
    hits = _lint().find_violations(CYCLE, "<cycle>")
    assert any("lock-order cycle" in what for _, what in hits), hits


def test_lock_order_cycle_suppressible():
    ok = CYCLE.replace(
        "with self._a_lock:\n                pass",
        "with self._a_lock:  # lock-order: ok — rev() only runs "
        "single-threaded at shutdown\n                pass",
    )
    assert ok != CYCLE
    assert _lint().find_violations(ok, "<waived>") == []


# ----------------------------------------------- check 3: blocking under lock


BLOCKING = textwrap.dedent(
    """
    import threading
    import time

    class Pinger:
        def __init__(self):
            self._lock = threading.Lock()
            self.last = 0.0

        def _loop(self):
            with self._lock:
                time.sleep(1.0)
                self.last = time.time()
    """
)


def test_blocking_under_lock_flagged():
    hits = _lint().find_violations(BLOCKING, "<sleep>")
    assert any("holding" in what for _, what in hits), hits


def test_blocking_under_lock_suppressible():
    ok = BLOCKING.replace(
        "time.sleep(1.0)",
        "time.sleep(1.0)  # blocking: ok — lock is private to this loop",
    )
    assert _lint().find_violations(ok, "<waived>") == []


# ------------------------------------------------------------ whole-tree gate


def test_concurrency_lint_tree_clean():
    """tools/check_concurrency.py runs clean over maggy_tpu/ — this is the
    tier-1 wiring, beside the host-sync / telemetry-name / docs-nav lints."""
    lint = _lint()
    violations = lint.check_tree(os.path.join(REPO, "maggy_tpu"))
    assert violations == [], violations


def test_required_models_protected():
    """Stripping any one lock annotation from a REQUIRED module reintroduces
    violations — the discipline cannot silently rot."""
    lint = _lint()
    sched = os.path.join(REPO, "maggy_tpu", "serve", "scheduler.py")
    with open(sched, encoding="utf-8") as f:
        source = f.read()
    stripped = source.replace("# guarded-by: _lock", "")
    assert stripped != source
    assert lint.find_violations(stripped, sched)


def test_required_model_missing_lock_flagged(tmp_path):
    lint = _lint()
    fake = tmp_path / "maggy_tpu" / "serve"
    fake.mkdir(parents=True)
    (fake / "scheduler.py").write_text(
        "class Scheduler:\n    def _loop(self):\n        pass\n"
    )
    violations = lint.check_tree(str(tmp_path / "maggy_tpu"))
    assert any(
        "required concurrency model missing" in what for _, _, what in violations
    ), violations


# ------------------------------------------------------- check_all registry


def test_check_all_registry_complete():
    """Every tools/check_*.py is registered in check_all.LINTS and every
    registered lint exists on disk — a new lint cannot dodge the suite."""
    check_all = _load("check_all")
    discovered = set(check_all.discovered_paths())
    registered = set(check_all.LINTS)
    assert discovered == registered, (
        f"unregistered lints: {sorted(discovered - registered)}; "
        f"stale registry entries: {sorted(registered - discovered)}"
    )
    for path in check_all.registered_paths().values():
        assert os.path.exists(path), path


def test_check_all_list_mode():
    check_all = _load("check_all")
    assert check_all.main(["--list"]) == 0


# ------------------------------------------------------ runtime lock order


def _lockdebug(monkeypatch):
    from maggy_tpu.core import lockdebug

    monkeypatch.setenv(lockdebug.ENV_VAR, "1")
    lockdebug.reset()
    return lockdebug


def test_lockdebug_disabled_returns_plain_locks(monkeypatch):
    from maggy_tpu.core import lockdebug

    monkeypatch.delenv(lockdebug.ENV_VAR, raising=False)
    assert not lockdebug.enabled()
    assert not isinstance(lockdebug.lock("x"), lockdebug.OrderedLock)
    assert not isinstance(lockdebug.rlock("y"), lockdebug.OrderedLock)


def test_lockdebug_catches_inversion(monkeypatch):
    ld = _lockdebug(monkeypatch)
    a, b = ld.lock("test.a"), ld.lock("test.b")
    with a:
        with b:
            pass
    with pytest.raises(ld.LockOrderError):
        with b:
            with a:
                pass
    assert "test.a" in ld.observed_order().get("test.b", ()) or True
    ld.reset()
    assert ld.observed_order() == {}


def test_lockdebug_rlock_reentrant(monkeypatch):
    ld = _lockdebug(monkeypatch)
    r = ld.rlock("test.r")
    with r:
        with r:  # recursion is not an inversion
            pass


def test_lockdebug_condition_wait_notify(monkeypatch):
    ld = _lockdebug(monkeypatch)
    cond = ld.condition("test.cond")
    hit = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hit.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.time() + 5
    while not t.is_alive() and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.05)
    with cond:
        cond.notify_all()
    t.join(5)
    assert hit == [1]


def test_fleet_locks_ordered_under_env(monkeypatch):
    """The serve-stack locks route through lockdebug: with the env flag on,
    a freshly built Telemetry recorder's locks are OrderedLock — the same
    wiring the chaos/fleet tests run under MAGGY_TPU_LOCK_ORDER=1 — and the
    real flush-from-two-threads pattern holds up under the assertion."""
    ld = _lockdebug(monkeypatch)
    from maggy_tpu.telemetry.recorder import Telemetry

    tel = Telemetry(worker="lint", role="worker")
    assert isinstance(tel._rpc_lock, ld.OrderedLock)
    assert isinstance(tel._flush_lock, ld.OrderedLock)

    stop = threading.Event()

    def beat():
        while not stop.is_set():
            tel.rpc("BEAT", 1.0)
            tel.snapshot()
            tel.flush()

    t = threading.Thread(target=beat)
    t.start()
    try:
        for _ in range(200):
            tel.rpc("STEP", 0.5)
            tel.count("steps")
        tel.snapshot()
    finally:
        stop.set()
        t.join(5)
    assert tel.snapshot()["rpc"]["STEP"]["n"] == 200
