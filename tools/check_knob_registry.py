#!/usr/bin/env python
"""Lint: every autopilot knob reference must be in the checked-in registry.

Mirrors ``tools/check_telemetry_names.py`` for the autopilot's config
surface. The failure mode it kills: the Planner emits a move for a knob
nothing applies (a typo silently becomes a no-op that still burns a guard
window), or a knob enters the playbook without declared bounds and a
safe-live contract.

* ``maggy_tpu/autopilot/knobs.py`` is the registry — a ``KNOBS`` table of
  name → ``Knob(kind, bounds/choices, safe_live, scope)`` plus a
  ``validate_registry()`` structural self-check (run here first: a knob
  with missing bounds or an unprefixed name fails the lint even if nothing
  references it).
* This tool AST-walks ``maggy_tpu/`` and checks:
  - every ``Move(...)`` call whose knob argument (first positional or
    ``knob=``) is a string literal names a registered knob;
  - every subscript ``KNOBS["..."]`` resolves;
  - inside ``maggy_tpu/autopilot/``, every string literal shaped like a
    knob name (``train.…``/``serve.…``/``fleet.…`` identifiers) is
    registered — the playbook and targets live there, so a dotted literal
    in that package IS a knob reference. (Telemetry names are exempt: the
    ``autopilot.*`` prefix does not match the knob scopes.)

Usage: ``python tools/check_knob_registry.py [root]`` — exits nonzero
listing violations. Built on the shared ``tools/analysis`` framework
(docs/static_analysis.md); wired into the tier-1 run via
``tests/test_autopilot.py``, beside the telemetry-name, host-sync,
exception-hygiene, bare-print, and docs-nav lints.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Tuple

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import (  # noqa: E402
    load_module_from_path,
    report,
    repo_root,
    walk_sources,
)

KNOB_PATTERN = re.compile(r"^(train|serve|fleet)\.[a-z][a-z0-9_]*$")


def load_registry(repo: str):
    """Load knobs.py by path (no package import — it must stay stdlib-only)."""
    return load_module_from_path(
        "maggy_tpu_knob_registry",
        os.path.join(repo, "maggy_tpu", "autopilot", "knobs.py"),
    )


def _literal(node) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return ""


def check_source(
    source: str, path: str, registry, in_autopilot_pkg: bool
) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    known = registry.KNOBS
    tree = ast.parse(source, filename=path)
    for node in ast.walk(tree):
        # Move("<knob>", ...) / Move(knob="<knob>", ...)
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.id if isinstance(fn, ast.Name) else getattr(fn, "attr", "")
            if name == "Move":
                knob = ""
                if node.args:
                    knob = _literal(node.args[0])
                for kw in node.keywords:
                    if kw.arg == "knob":
                        knob = _literal(kw.value)
                if knob and knob not in known:
                    out.append(
                        (
                            node.lineno,
                            f"Move({knob!r}) targets an unregistered knob — "
                            "declare it in maggy_tpu/autopilot/knobs.py",
                        )
                    )
        # KNOBS["<knob>"]
        if isinstance(node, ast.Subscript):
            base = node.value
            base_name = (
                base.id if isinstance(base, ast.Name) else getattr(base, "attr", "")
            )
            if base_name == "KNOBS":
                knob = _literal(node.slice)
                if knob and knob not in known:
                    out.append(
                        (node.lineno, f"KNOBS[{knob!r}] is not registered")
                    )
        # inside the autopilot package any knob-shaped literal is a reference
        if in_autopilot_pkg and isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, str) and KNOB_PATTERN.match(v) and v not in known:
                out.append(
                    (
                        node.lineno,
                        f"knob-shaped literal {v!r} is not in the registry — "
                        "register it or rename the string",
                    )
                )
    return out


def check_tree(root: str, registry) -> List[Tuple[str, int, str]]:
    ap_pkg = os.path.join("maggy_tpu", "autopilot")
    return walk_sources(
        root,
        lambda source, path: check_source(
            source, path, registry, in_autopilot_pkg=ap_pkg in path
        ),
    )


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    repo = repo_root()
    root = args[0] if args else os.path.join(repo, "maggy_tpu")
    registry = load_registry(repo)
    violations = [
        (os.path.join(repo, "maggy_tpu", "autopilot", "knobs.py"), 0, err)
        for err in registry.validate_registry()
    ]
    violations.extend(check_tree(root, registry))
    return report(violations)


if __name__ == "__main__":
    sys.exit(main())
