"""Full measurement playbook for tunnel recovery (VERDICT r4 item 1).

Run by tools/tpu_watchdog.sh the moment the TPU tunnel answers a liveness
probe. Executes the whole staged-perf validation sequence as child
processes — sequentially, with NO timeout kills (killing a client
mid-compile wedges the tunnel for everyone) — and persists every artifact
under tools/ so a later round can read the numbers even if this process's
session is over:

  1. bench.py                      -> tools/bench_early_r5.json (+ snapshot)
  2. tune_flash.py --emit          -> tools/flash_tuned_r5.json (bwd tiles)
  3. batch-size sweep {16, 32} with the tuned tiles
                                   -> tools/bench_bs{N}_r5.json
     winner                        -> tools/tuned_bench.json  (bench.py
                                      auto-applies this at round-end)
  4. bench_decode.py               -> tools/bench_decode_r5.json
  5. examples/resnet_asha.py       -> tools/resnet_asha_r5.log
  6. profile_step.py               -> tools/profile_r5/ (trace for analysis)

    python tools/tpu_playbook.py
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
TOOLS = os.path.join(ROOT, "tools")
LOG = os.path.join(TOOLS, "tpu_playbook.log")


def note(msg: str) -> None:
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def run(cmd, out_path=None, env_extra=None):
    """Run a child to completion (never killed — tunnel safety). The full
    combined stream goes to <out_path>.log; when out_path ends in .json only
    the last parseable JSON line is written there, so artifact files stay
    json.load-able even when warnings precede the result line. Returns
    (rc, last_json_or_None)."""
    env = dict(os.environ)
    env.setdefault("PYTHONUNBUFFERED", "1")
    if env_extra:
        env.update({k: str(v) for k, v in env_extra.items()})
    note(f"run: {' '.join(cmd)} env+={env_extra or {}}")
    proc = subprocess.run(
        cmd, cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    parsed = None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except ValueError:
                continue

    def is_real(d):
        if d is None:
            return False
        extra = d.get("extra", d)
        return not extra.get("cpu_fallback", False)

    if out_path:
        if out_path.endswith(".json"):
            base = os.path.splitext(out_path)[0]
            # a watchdog retry that flakes back to CPU must not clobber a
            # prior attempt's real-silicon artifact — reroute junk aside
            try:
                with open(out_path) as f:
                    prior_real = is_real(json.load(f))
            except (OSError, ValueError):
                prior_real = False
            if parsed is None:
                with open(base + ".failed.log", "w") as f:
                    f.write(proc.stdout)
            elif prior_real and not is_real(parsed):
                note(f"  keeping prior real artifact {out_path}; new run was CPU junk")
                with open(base + ".rejected.log", "w") as f:
                    f.write(proc.stdout)
            else:
                with open(base + ".log", "w") as f:
                    f.write(proc.stdout)
                with open(out_path, "w") as f:
                    json.dump(parsed, f)
        else:
            with open(out_path, "w") as f:
                f.write(proc.stdout)
    note(f"  rc={proc.returncode} json={'yes' if parsed else 'no'}")
    return proc.returncode, parsed


def main() -> int:
    py = sys.executable
    note("playbook start")

    # Measure from a clean slate: a prior attempt's tuning must not leak into
    # this run's baselines or be mistaken for a fresh measurement. Restored
    # on abort — and, for a prior attempt that crashed between move and
    # rewrite, at startup — so a failed attempt never loses measured tuning.
    moved = []
    for stale in ("tuned_bench.json", "flash_tuned_r5.json"):
        path = os.path.join(TOOLS, stale)
        if os.path.exists(path + ".prev") and not os.path.exists(path):
            os.replace(path + ".prev", path)
            note(f"recovered {stale} stranded as .prev by a crashed attempt")
        if os.path.exists(path):
            os.replace(path, path + ".prev")
            moved.append(path)
            note(f"moved stale {stale} -> {stale}.prev")

    def restore_prev():
        for path in moved:
            if not os.path.exists(path):
                os.replace(path + ".prev", path)
                note(f"restored {os.path.basename(path)} from .prev")

    # 1. baseline bench: default-bs untiled, full metrics (snapshots if real)
    rc, early = run([py, "bench.py"], os.path.join(TOOLS, "bench_early_r5.json"))
    if rc != 0 or early is None or early.get("extra", {}).get("cpu_fallback"):
        note("bench failed or fell back to CPU; aborting silicon sweep")
        restore_prev()
        return 1

    # 2. flash backward-tile sweep on silicon
    flash_json = os.path.join(TOOLS, "flash_tuned_r5.json")
    run(
        [py, "tools/tune_flash.py", "--seq", "1024", "--steps", "10",
         "--emit", flash_json],
        os.path.join(TOOLS, "tune_flash_r5.log"),
    )
    tiles = {}
    try:
        with open(flash_json) as f:
            win = json.load(f)
        sys.path.insert(0, ROOT)
        from maggy_tpu.ops.flash import _auto_blocks

        # bwd tiles default to the fwd tiles, which at the bench geometry
        # come from _auto_blocks — a "winner" equal to that default changes
        # nothing, so don't burn tunnel minutes re-benching it
        default_q, default_k = _auto_blocks(1024, 1024)
        if (win["bwd_block_q"], win["bwd_block_k"]) == (default_q, default_k):
            note(f"flash bwd winner {win} == auto default; skipping tiled runs")
        else:
            tiles = {
                "MAGGY_TPU_FLASH_BWD_Q": win["bwd_block_q"],
                "MAGGY_TPU_FLASH_BWD_K": win["bwd_block_k"],
            }
            note(f"flash bwd winner: {win}")
    except (OSError, ValueError, KeyError):
        note("no flash winner emitted (cpu or sweep failure); tiles unset")

    # 3. config sweep (--train-only skips the ASHA/ring secondary benches —
    # tunnel-alive minutes are the scarce resource). The untiled step-1
    # baseline competes too, so microbench tile "wins" that regress the full
    # train step are rejected rather than persisted.
    base_bs = early.get("extra", {}).get("batch_size_per_chip", 16)
    candidates = [(bs, {}) for bs in (16, 32) if bs != base_bs]
    if tiles:
        candidates += [(16, tiles), (32, tiles)]
    best = (base_bs, {}, early["value"])  # step-1 baseline, as actually run
    note(f"baseline bs={base_bs} untiled: {early['value']} tok/s/chip")
    for bs, t in candidates:
        _, res = run(
            [py, "bench.py", "--train-only"],
            os.path.join(TOOLS, f"bench_bs{bs}{'_tiled' if t else ''}_r5.json"),
            env_extra={"MAGGY_TPU_BENCH_BS": bs, **t},
        )
        if not res or res.get("extra", {}).get("cpu_fallback"):
            continue
        note(f"bs={bs} tiles={bool(t)}: {res['value']} tok/s/chip")
        if res["value"] > best[2]:
            best = (bs, t, res["value"])
    tuned = {"batch_size": best[0], "value": best[2]}
    if best[1]:
        tuned["bwd_block_q"] = best[1]["MAGGY_TPU_FLASH_BWD_Q"]
        tuned["bwd_block_k"] = best[1]["MAGGY_TPU_FLASH_BWD_K"]
    with open(os.path.join(TOOLS, "tuned_bench.json"), "w") as f:
        json.dump(tuned, f)
    note(f"tuned_bench.json written: {tuned}")

    # 3b. full bench at the winning config — lands the snapshot record with
    # ASHA + ring secondary metrics included (train-only runs never snapshot)
    if best[:2] != (base_bs, {}):
        run([py, "bench.py"], os.path.join(TOOLS, "bench_tuned_r5.json"))

    # 4. decode throughput table
    run([py, "tools/bench_decode.py"],
        os.path.join(TOOLS, "bench_decode_r5.json"))

    # 4b. long-context attention table (flash vs blockwise vs dense at
    # S up to 16k) + a full-model S=8192 train step
    run([py, "tools/bench_longcontext.py"],
        os.path.join(TOOLS, "bench_longcontext_r5.json"))

    # 5. real-train_fn ASHA (BASELINE config 2 in miniature) on silicon
    run([py, "examples/resnet_asha.py"],
        os.path.join(TOOLS, "resnet_asha_r5.log"))

    # 6. profiler trace of the bench train step for later analysis
    run([py, "tools/profile_step.py"],
        os.path.join(TOOLS, "profile_step_r5.log"))

    note("playbook done")
    # if the tunnel died mid-playbook the artifacts above are CPU junk; tell
    # the watchdog to keep probing for a genuine recovery
    sys.path.insert(0, ROOT)
    from maggy_tpu.util import backend_alive

    alive = backend_alive(150)
    note(f"final liveness: {'alive' if alive else 'dead'}")
    return 0 if alive else 1


if __name__ == "__main__":
    sys.exit(main())
