#!/bin/bash
# TPU tunnel watchdog: probe liveness every ~7 min; on first success run
# bench.py (never timeout-killed — killing a client mid-compile wedges the
# tunnel) so BENCH_TPU_SNAPSHOT.json captures a real-hardware record early.
# Writes status lines to tools/tpu_watchdog.log (gitignored).
cd /root/repo
LOG=tools/tpu_watchdog.log
echo "$(date -u +%FT%TZ) watchdog start" >> "$LOG"
for i in $(seq 1 200); do
  if python -c "
from maggy_tpu.util import backend_alive
import sys
sys.exit(0 if backend_alive(150) else 1)
"; then
    echo "$(date -u +%FT%TZ) tunnel ALIVE (probe $i); running bench" >> "$LOG"
    python bench.py > tools/bench_early_r4.json 2> tools/bench_early_r4.err
    echo "$(date -u +%FT%TZ) bench rc=$? done; running decode bench" >> "$LOG"
    python tools/bench_decode.py > tools/bench_decode_r4.json 2> tools/bench_decode_r4.err
    echo "$(date -u +%FT%TZ) decode bench rc=$? done" >> "$LOG"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) probe $i dead; sleeping 420s" >> "$LOG"
  sleep 420
done
