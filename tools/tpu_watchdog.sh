#!/bin/bash
# TPU tunnel watchdog (round 5): probe liveness every ~7 min; on first
# success run the FULL measurement playbook (tools/tpu_playbook.py: bench +
# flash bwd-tile sweep + bs sweep + decode + real-train ASHA + profile
# trace). Children are never timeout-killed — killing a client mid-compile
# wedges the tunnel. One watchdog only; writes tools/tpu_watchdog.log
# (gitignored).
cd /root/repo
LOG=tools/tpu_watchdog.log
echo "$(date -u +%FT%TZ) r5 watchdog start" >> "$LOG"
for i in $(seq 1 200); do
  if python -c "
from maggy_tpu.util import backend_alive
import sys
sys.exit(0 if backend_alive(150) else 1)
"; then
    echo "$(date -u +%FT%TZ) tunnel ALIVE (probe $i); running playbook" >> "$LOG"
    python tools/tpu_playbook.py >> tools/tpu_playbook.stdout 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) playbook rc=$rc done" >> "$LOG"
    # rc!=0 = the backend fell back / died mid-playbook (false-positive
    # probe); keep probing so a later genuine recovery still gets benched
    [ "$rc" -eq 0 ] && exit 0
    echo "$(date -u +%FT%TZ) playbook failed on live probe $i; will re-probe" >> "$LOG"
  else
    echo "$(date -u +%FT%TZ) r5 probe $i dead; sleeping 420s" >> "$LOG"
  fi
  sleep 420
done
