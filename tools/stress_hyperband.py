"""Controller-level scheduling stress (VERDICT r4 item 6): does Hyperband
keep a 16-executor fleet busy at 256-trial scale, with stragglers?

Simulates the driver's scheduling loop against the REAL controllers (the
same get_suggestion path `core/driver/hpo.py _try_assign` drives, one
decision at a time on one thread — the production discipline) under a
synthetic oracle: trial runtime = budget × unit, with a straggler fraction
running 8× slower. Records, per configuration:

* executor-idle fraction (idle executor-seconds / fleet-seconds to makespan)
* simulated makespan
* controller decisions/second of real Python time (the `_pending` question:
  the gate is consumed within one get_suggestion call, so the measurement
  shows whether serialized decisions could ever throttle a fleet)

Configurations: Hyperband with concurrent cycles (iterations=N — later
cycles' base rungs fill the straggler-gated idle), the same budget as
SERIAL cycles (the pre-`iterations` behavior), and ASHA at a matched trial
count.

    python tools/stress_hyperband.py [--executors 16] [--straggler 0.05]
"""

import argparse
import heapq
import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.optimizer import IDLE, get_optimizer
from maggy_tpu.pruner.hyperband import Hyperband
from maggy_tpu.searchspace import Searchspace
from maggy_tpu.trial import Trial


def simulate(controller_factory, n_executors: int, straggler_frac: float,
             seed: int = 0):
    """Run one synthetic experiment to completion; return the stats dict."""
    import random

    py_rng = random.Random(seed)
    trial_store = {}
    final_store = []
    controller = controller_factory(trial_store, final_store)

    clock = 0.0
    busy_until = [0.0] * n_executors
    busy_time = [0.0] * n_executors
    events = []  # (finish_time, executor, trial)
    idle_execs = set(range(n_executors))
    decisions = 0
    py_time = 0.0
    finished_last = None

    def try_fill():
        nonlocal decisions, py_time, finished_last
        progressed = True
        while idle_execs and progressed:
            progressed = False
            ex = min(idle_execs)
            t0 = time.perf_counter()
            suggestion = controller.get_suggestion(finished_last)
            py_time += time.perf_counter() - t0
            finished_last = None
            decisions += 1
            if isinstance(suggestion, Trial):
                budget = suggestion.params.get("budget") or 1.0
                runtime = float(budget)
                if py_rng.random() < straggler_frac:
                    runtime *= 8.0  # straggler
                suggestion.schedule(ex)
                trial_store[suggestion.trial_id] = suggestion
                heapq.heappush(events, (clock + runtime, ex, suggestion))
                busy_until[ex] = clock + runtime
                busy_time[ex] += runtime
                idle_execs.discard(ex)
                progressed = True
            elif suggestion == IDLE:
                break  # nothing schedulable until something finishes
            else:  # None: controller exhausted
                break

    try_fill()
    while events:
        clock, ex, trial = heapq.heappop(events)
        trial_store.pop(trial.trial_id, None)
        trial.begin()
        trial.finalize(py_rng.random())
        final_store.append(trial)
        idle_execs.add(ex)
        finished_last = trial
        try_fill()

    makespan = max(busy_until) if any(busy_until) else 0.0
    fleet_seconds = makespan * n_executors
    idle_frac = 1.0 - (sum(busy_time) / fleet_seconds) if fleet_seconds else 0.0
    return {
        "trials": len(final_store),
        "makespan": round(makespan, 2),
        "idle_fraction": round(idle_frac, 4),
        "decisions": decisions,
        "decisions_per_sec_python": round(decisions / py_time, 1) if py_time else None,
        "controller_s_per_decision_us": round(py_time / decisions * 1e6, 1),
    }


def hyperband_factory(iterations: int, seed: int = 0):
    def make(trial_store, final_store):
        def metric_getter(trial_ids):
            if isinstance(trial_ids, str):
                trial_ids = [trial_ids]
            return {
                t.trial_id: t.final_metric
                for t in final_store
                if t.trial_id in trial_ids
            }

        pruner = Hyperband(
            trial_metric_getter=metric_getter, eta=3, resource_min=1,
            resource_max=9, direction="max", iterations=iterations,
        )
        controller = get_optimizer("randomsearch", seed=seed)
        controller.setup(
            Searchspace(x=("DOUBLE", [0.0, 1.0])),
            pruner.num_trials(),
            trial_store,
            final_store,
            direction="max",
            pruner=pruner,
        )
        return controller

    return make


def asha_factory(num_trials: int, seed: int = 0):
    def make(trial_store, final_store):
        controller = get_optimizer(
            "asha", seed=seed, reduction_factor=3, resource_min=1, resource_max=9
        )
        controller.setup(
            Searchspace(x=("DOUBLE", [0.0, 1.0])),
            num_trials,
            trial_store,
            final_store,
            direction="max",
        )
        return controller

    return make


def run_suite(n_executors: int = 16, straggler: float = 0.05, cycles: int = 12,
              seed: int = 0):
    """The VERDICT r4 item 6 comparison; ~22 trials/cycle x 12 = 264 ≈ the
    256-trial bar."""
    concurrent = simulate(
        hyperband_factory(iterations=cycles, seed=seed), n_executors, straggler,
        seed=seed,
    )
    # pre-`iterations` behavior: the same budget as strictly serial cycles
    serial_total = {"trials": 0, "makespan": 0.0, "decisions": 0}
    idle_accum = 0.0
    for c in range(cycles):
        r = simulate(
            hyperband_factory(iterations=1, seed=seed + c), n_executors,
            straggler, seed=seed + c,
        )
        serial_total["trials"] += r["trials"]
        serial_total["makespan"] += r["makespan"]
        serial_total["decisions"] += r["decisions"]
        idle_accum += r["idle_fraction"] * r["makespan"]
    serial_total["idle_fraction"] = round(
        idle_accum / serial_total["makespan"], 4
    )
    serial_total["makespan"] = round(serial_total["makespan"], 2)
    asha = simulate(
        asha_factory(num_trials=concurrent["trials"], seed=seed), n_executors,
        straggler, seed=seed,
    )
    return {
        "n_executors": n_executors,
        "straggler_fraction": straggler,
        "hyperband_concurrent_cycles": concurrent,
        "hyperband_serial_cycles": serial_total,
        "asha_matched_trials": asha,
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--executors", type=int, default=16)
    parser.add_argument("--straggler", type=float, default=0.05)
    parser.add_argument("--cycles", type=int, default=12)
    args = parser.parse_args()
    print(json.dumps(run_suite(args.executors, args.straggler, args.cycles)))


if __name__ == "__main__":
    main()
