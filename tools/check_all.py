#!/usr/bin/env python
"""Single entry point for every static-analysis lint in tools/.

``python tools/check_all.py`` runs the whole suite and exits nonzero when
any lint reports violations; ``--list`` prints the registry. Each lint is
a ``check_*.py`` module exposing ``main(argv=None) -> int`` (0 = clean) —
the registry below is the authoritative list, and a tier-1 test asserts
every ``tools/check_*.py`` on disk is registered so a new lint cannot be
added without joining the suite.
"""
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import load_module_from_path  # noqa: E402

# lint name -> one-line purpose; name must match tools/check_<name>.py
LINTS = {
    "chaos_kinds": "chaos fault kinds used in tests exist in the registry",
    "concurrency": "lock discipline: shared state, lock order, blocking under lock",
    "docs_nav": "every docs/*.md page is reachable from the mkdocs nav",
    "exception_hygiene": "no silent broad excepts outside the allowlist",
    "host_sync": "no host-sync (device_get/block_until_ready) in hot regions",
    "knob_registry": "autopilot knobs referenced in code exist in the registry",
    "no_bare_print": "no bare print() — output routes through telemetry",
    "telemetry_names": "telemetry metric/alert names match the registry",
}


def registered_paths():
    return {
        name: os.path.join(_TOOLS_DIR, f"check_{name}.py") for name in LINTS
    }


def discovered_paths():
    return {
        fn[len("check_"):-len(".py")]: os.path.join(_TOOLS_DIR, fn)
        for fn in sorted(os.listdir(_TOOLS_DIR))
        if fn.startswith("check_") and fn.endswith(".py") and fn != "check_all.py"
    }


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if "--list" in args:
        for name, what in sorted(LINTS.items()):
            print(f"check_{name}: {what}")
        return 0
    missing = set(discovered_paths()) - set(LINTS)
    if missing:
        for name in sorted(missing):
            print(
                f"tools/check_{name}.py exists but is not in check_all.LINTS",
                file=sys.stderr,
            )
        return 1
    failed = []
    for name, path in sorted(registered_paths().items()):
        mod = load_module_from_path(f"check_{name}", path)
        rc = mod.main([])
        status = "ok" if rc == 0 else "FAIL"
        print(f"check_{name}: {status}", file=sys.stderr)
        if rc != 0:
            failed.append(name)
    if failed:
        print(f"{len(failed)} lint(s) failed: {', '.join(failed)}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
