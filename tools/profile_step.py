"""Capture a profiler trace of the bench-geometry train step (VERDICT r4
item 1: "profile one train step"). Writes a TensorBoard-readable trace to
tools/profile_r5/ for MFU-gap analysis on live silicon.

    python tools/profile_step.py [--steps 5]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=5)
    parser.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "profile_r5"),
    )
    args = parser.parse_args()

    from bench import apply_tuned_config, bench_setup, ensure_live_backend

    cpu = ensure_live_backend()
    apply_tuned_config()

    import jax

    # shared with bench.py — the trace is only useful if it profiles exactly
    # the step (model, sharding, optimizer, batch, warmup) the record was
    # set on; compile happens inside bench_setup, outside the trace
    trainer, state, batch, cfg, batch_size, seq_len = bench_setup(cpu)

    os.makedirs(args.out, exist_ok=True)
    with jax.profiler.trace(args.out):
        for _ in range(args.steps):
            state, m = trainer.step(state, batch)
        float(m["loss"])
    print(f"trace written to {args.out} ({args.steps} steps, cpu={cpu})")
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = trainer.step(state, batch)
    float(m["loss"])
    print(f"untraced step: {(time.perf_counter() - t0) / args.steps * 1e3:.2f} ms")


if __name__ == "__main__":
    sys.exit(main())
