"""The reference's HEADLINE number, reproduced on this framework.

The upstream project's only published benchmark: asynchronous trial
assignment completes a fixed random-search budget in **33-58% less
wall-clock time** than synchronous Spark BSP execution, with no accuracy
loss (DistributedML'20, DOI 10.1145/3426745.3431338; the claim's mechanism
is "executors always busy" — docs/hpo/intro.md:1-13).

This harness measures the same comparison here: a real ``lagom()``
random-search run (driver + RPC + executor threads — the actual async
control plane) over heterogeneous-duration trials, against the synchronous
BSP wall-clock computed from the SAME per-trial durations (waves of
``num_executors``, each gated on its slowest member — exactly what a BSP
stage barrier costs). Prints one JSON line.

    python tools/bench_async_vs_bsp.py [--trials 64] [--executors 8]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()


DISTRIBUTIONS = {
    # durations long enough that the one-time driver bring-up (~0.4 s)
    # doesn't distort the steady-state async-vs-BSP comparison
    "uniform": lambda x: 0.1 + 0.9 * x,          # 0.1-1.0 s
    # heavy tail: most trials fast, a few 10x slower — real NN trials with
    # uneven convergence/early stops, the regime the paper's upper band
    # comes from (a BSP wave is as slow as its slowest member)
    "heavy_tail": lambda x: 0.1 + 1.5 * x**3,    # 0.1-1.6 s, skewed
}


def run_async(num_trials: int, num_executors: int, dist: str, seed: int = 0):
    """One real lagom() run; trial duration rides the searchspace so the
    driver's scheduling order decides which executor sleeps how long."""
    import importlib

    experiment = importlib.import_module("maggy_tpu.experiment")
    from maggy_tpu import Searchspace
    from maggy_tpu.config import HyperparameterOptConfig

    durations = []
    duration_of = DISTRIBUTIONS[dist]

    def train(hparams, reporter):
        d = duration_of(float(hparams["x"]))
        reporter.broadcast(float(hparams["x"]), step=0)
        t0 = time.perf_counter()
        time.sleep(d)
        # record (start, ACTUAL elapsed): elapsed (not requested) so sleep
        # overshoot on a loaded host taxes the BSP baseline too; start so
        # BSP waves form in ASSIGNMENT order — completion order is roughly
        # sorted ascending, and similar-duration waves would understate the
        # BSP cost a real submission-ordered barrier pays
        durations.append((t0, time.perf_counter() - t0))
        return {"metric": float(hparams["x"])}

    t0 = time.perf_counter()
    result = experiment.lagom(
        train,
        HyperparameterOptConfig(
            num_trials=num_trials,
            optimizer="randomsearch",
            searchspace=Searchspace(x=("DOUBLE", [0.0, 1.0])),
            direction="max",
            es_policy="none",
            num_executors=num_executors,
            hb_interval=0.05,
            seed=seed,
        ),
    )
    wall = time.perf_counter() - t0
    assert result["num_trials"] == num_trials, result
    # assignment order, not completion order (see comment in train)
    durations.sort(key=lambda sd: sd[0])
    return wall, [elapsed for _, elapsed in durations]


def bsp_wall(durations, num_executors: int) -> float:
    """Synchronous BSP cost of the SAME trials: waves of num_executors,
    each wave as slow as its slowest trial (the Spark stage barrier)."""
    total = 0.0
    for i in range(0, len(durations), num_executors):
        total += max(durations[i : i + num_executors])
    return total


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--trials", type=int, default=96)
    parser.add_argument("--executors", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    rows = {}
    for dist in DISTRIBUTIONS:
        async_wall, durations = run_async(
            args.trials, args.executors, dist, args.seed
        )
        sync_wall = bsp_wall(durations, args.executors)
        rows[dist] = {
            "reduction_pct": round((1.0 - async_wall / sync_wall) * 100, 1),
            "async_wall_s": round(async_wall, 2),
            "bsp_wall_s": round(sync_wall, 2),
            "work_s": round(sum(durations), 2),
            "ideal_wall_s": round(sum(durations) / args.executors, 2),
        }
    best = max(r["reduction_pct"] for r in rows.values())
    print(json.dumps({
        "metric": "async_vs_bsp_wallclock_reduction",
        "value": best,
        "unit": "% less wall-clock than synchronous BSP",
        # the reference's published band is 33-58% (DistributedML'20);
        # >= 1.0 means the heavy-tail regime lands inside-or-above it
        "vs_baseline": round(best / 33.0, 2),
        "extra": {
            "trials": args.trials,
            "executors": args.executors,
            **rows,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
