#!/usr/bin/env python
"""Attribute where time went, per request and per training step, from the
merged telemetry JSONL a run leaves behind.

Thin CLI over :mod:`maggy_tpu.telemetry.attribution` — the SAME code path
the autopilot Diagnoser (``maggy_tpu/autopilot/diagnose.py``) consumes, so
the human report and the continuous-tuning loop always read identical
numbers. ``--json`` prints the attribution as machine-readable JSON with a
stable, versioned layout (``schema`` field; see the attribution module
docstring for the field contract).

Usage::

    python tools/analyze_trace.py <exp_dir | telemetry_dir> [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.telemetry.attribution import (  # noqa: E402
    COMPONENT_ORDER,
    GAP_LABELS,  # noqa: F401 - re-exported for consumers of the old tool API
    SCHEMA,  # noqa: F401
    TERMINALS,  # noqa: F401
    analyze,
    attribute_requests,  # noqa: F401
    attribute_steps,  # noqa: F401
    iter_jsonl_files,  # noqa: F401
    load_records,  # noqa: F401
    summarize_requests,  # noqa: F401
)

# ----------------------------------------------------------------- reporting


def _fmt_ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{v:8.1f}"


def render_report(rows, req_summary, step_summary, max_rows: int = 24) -> str:
    lines: List[str] = []
    if rows:
        lines.append(
            f"== per-request attribution ({len(rows)} request(s), ms) =="
        )
        header = ["trace", "state", "hops"] + list(COMPONENT_ORDER) + ["e2e"]
        lines.append("  ".join(f"{h:>8}" for h in header))
        for row in rows[:max_rows]:
            cells = [f"{row['trace'][:8]:>8}", f"{row['state']:>8}", f"{row['hops']:>8}"]
            for k in COMPONENT_ORDER:
                cells.append(_fmt_ms(row["components"].get(k)))
            cells.append(_fmt_ms(row["e2e_ms"]))
            lines.append("  ".join(cells))
        if len(rows) > max_rows:
            lines.append(f"  ... {len(rows) - max_rows} more")
        lines.append("")
        lines.append("mean per request:")
        for k in COMPONENT_ORDER:
            v = req_summary["components_ms_mean"].get(k)
            if v is None:
                continue
            share = req_summary["components_share"].get(k, 0.0)
            lines.append(f"  {k:>10}  {v:8.1f} ms  ({share * 100:5.1f}%)")
        lines.append(
            f"  {'e2e':>10}  {req_summary['e2e_ms_mean']:8.1f} ms  "
            f"(requeue hops: {req_summary['requeue_hops']})"
        )
    else:
        lines.append("no request lifecycle events found")
    if step_summary.get("steps"):
        lines.append("")
        lines.append(f"== per-step attribution ({step_summary['steps']} step(s)) ==")
        lines.append(f"  step wall     {_fmt_ms(step_summary['step_ms_mean'])} ms")
        lines.append(
            f"  input wait    {_fmt_ms(step_summary['input_wait_ms_mean'])} ms"
        )
        lines.append(
            f"  metrics drain {_fmt_ms(step_summary['metrics_drain_ms_mean'])} ms"
        )
        lines.append(
            f"  compute (est) {_fmt_ms(step_summary.get('compute_ms_est'))} ms"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("path", help="experiment dir or its telemetry/ subdir")
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable output (stable schema; see "
             "maggy_tpu/telemetry/attribution.py)",
    )
    args = parser.parse_args(argv)
    result = analyze(args.path)
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True, default=str))
    else:
        print(
            render_report(
                result["requests"], result["request_summary"], result["step_summary"]
            )
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
