#!/usr/bin/env python
"""Lint: ban bare ``print()`` inside ``maggy_tpu/``.

Framework code must route user-facing output through ``Reporter``/``Telemetry``
(worker side — prints there vanish from pod workers and bypass the log
shipping the driver aggregates) or ``Driver.log`` (driver side). A ``print``
is *bare* when it has no explicit ``file=`` argument: deliberate CLI/stderr
diagnostics (``print(..., file=sys.stderr)``) stay allowed, silent stdout
leaks do not.

Allowlisted files: ``reporter.py`` (owns the print tee itself) and
``monitor.py`` (a CLI whose stdout IS the product).

Usage: ``python tools/check_no_bare_print.py [root]`` — exits nonzero listing
violations. Wired into the tier-1 run via ``tests/test_telemetry.py``.
"""

from __future__ import annotations

import ast
import os
import sys

ALLOWED_FILES = {"reporter.py", "monitor.py"}


def find_bare_prints(source: str, path: str):
    """(line, col) of every print() call without an explicit file= kwarg."""
    out = []
    tree = ast.parse(source, filename=path)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
            and not any(kw.arg == "file" for kw in node.keywords)
        ):
            out.append((node.lineno, node.col_offset))
    return out


def check_tree(root: str):
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if not d.startswith((".", "_build"))]
        for name in sorted(filenames):
            if not name.endswith(".py") or name in ALLOWED_FILES:
                continue
            path = os.path.join(dirpath, name)
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
            except OSError:
                continue
            try:
                hits = find_bare_prints(source, path)
            except SyntaxError as e:
                violations.append((path, e.lineno or 0, f"syntax error: {e.msg}"))
                continue
            violations.extend((path, line, "bare print()") for line, _ in hits)
    return violations


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = args[0] if args else os.path.join(repo, "maggy_tpu")
    violations = check_tree(root)
    for path, line, what in violations:
        print(
            f"{path}:{line}: {what} — route through Reporter/Telemetry or "
            "pass an explicit file=",
            file=sys.stderr,
        )
    if violations:
        print(f"{len(violations)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
