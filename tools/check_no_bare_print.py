#!/usr/bin/env python
"""Lint: ban bare ``print()`` inside ``maggy_tpu/``.

Framework code must route user-facing output through ``Reporter``/``Telemetry``
(worker side — prints there vanish from pod workers and bypass the log
shipping the driver aggregates) or ``Driver.log`` (driver side). A ``print``
is *bare* when it has no explicit ``file=`` argument: deliberate CLI/stderr
diagnostics (``print(..., file=sys.stderr)``) stay allowed, silent stdout
leaks do not.

Allowlisted files: ``reporter.py`` (owns the print tee itself) and
``monitor.py`` (a CLI whose stdout IS the product).

Usage: ``python tools/check_no_bare_print.py [root]`` — exits nonzero listing
violations. Built on the shared ``tools/analysis`` framework
(docs/static_analysis.md); wired into the tier-1 run via
``tests/test_telemetry.py``.
"""

from __future__ import annotations

import ast
import os
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import report, repo_root, walk_sources  # noqa: E402

ALLOWED_FILES = {"reporter.py", "monitor.py"}


def find_bare_prints(source: str, path: str):
    """(line, col) of every print() call without an explicit file= kwarg."""
    out = []
    tree = ast.parse(source, filename=path)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
            and not any(kw.arg == "file" for kw in node.keywords)
        ):
            out.append((node.lineno, node.col_offset))
    return out


def _check_file(source: str, path: str):
    return [
        (
            line,
            "bare print() — route through Reporter/Telemetry or pass an "
            "explicit file=",
        )
        for line, _ in find_bare_prints(source, path)
    ]


def check_tree(root: str):
    return walk_sources(
        root,
        _check_file,
        skip=lambda path: os.path.basename(path) in ALLOWED_FILES,
    )


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.join(repo_root(), "maggy_tpu")
    return report(check_tree(root))


if __name__ == "__main__":
    sys.exit(main())
