#!/usr/bin/env python
"""Lint: ban silent exception swallows inside ``maggy_tpu/``.

A fault-tolerant runtime lives or dies by what it does with exceptions: the
resilience machinery (docs/resilience.md) classifies failures to decide
between retry and fail-fast, and a handler that silently eats an error
upstream starves that classification. Two patterns are flagged:

* **bare except** — ``except:`` catches ``SystemExit``/``KeyboardInterrupt``
  too and is never acceptable; name a type (``BaseException`` if you truly
  mean everything, with a comment saying why).
* **broad swallow** — ``except Exception:`` / ``except BaseException:``
  whose body is only ``pass``, with no justification. A deliberate swallow
  is fine — best-effort logging, optional backends — but it must say so: a
  trailing comment on the ``except`` line (or a comment line as the first
  thing in the handler body) acts as the per-site allowlist entry.

``ALLOWLIST`` below escapes whole files that legitimately cannot carry
markers (none today; add sparingly with a reason).

Usage: ``python tools/check_exception_hygiene.py [root]`` — exits nonzero
listing violations. Built on the shared ``tools/analysis`` framework
(docs/static_analysis.md); wired into the tier-1 run via
``tests/test_resilience.py``, beside ``check_no_bare_print.py`` and
``check_docs_nav.py``.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Set, Tuple

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import comment_lines, report, repo_root, walk_sources  # noqa: E402

# file basenames exempt from the whole check, with a reason each
ALLOWLIST: Set[str] = set()

BROAD_NAMES = ("Exception", "BaseException")


def _is_broad(type_node) -> bool:
    if isinstance(type_node, ast.Name):
        return type_node.id in BROAD_NAMES
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(el) for el in type_node.elts)
    return False


def find_violations(source: str, path: str) -> List[Tuple[int, str]]:
    """(line, description) for every unhygienic handler in ``source``."""
    tree = ast.parse(source, filename=path)
    comments = comment_lines(source)
    out: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append((node.lineno, "bare 'except:' — name an exception type"))
            continue
        only_pass = all(isinstance(stmt, ast.Pass) for stmt in node.body)
        if not (_is_broad(node.type) and only_pass):
            continue
        # justification: a comment on the except line itself, or any comment
        # line between it and the first body statement (inclusive)
        first_body = node.body[0].lineno
        if any(ln in comments for ln in range(node.lineno, first_body + 1)):
            continue
        out.append(
            (
                node.lineno,
                "broad silent swallow (except Exception: pass) without a "
                "justifying comment",
            )
        )
    return out


def check_tree(root: str) -> List[Tuple[str, int, str]]:
    return walk_sources(
        root,
        find_violations,
        skip=lambda path: os.path.basename(path) in ALLOWLIST,
    )


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.join(repo_root(), "maggy_tpu")
    return report(check_tree(root))


if __name__ == "__main__":
    sys.exit(main())
