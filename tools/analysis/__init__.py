"""Shared static-analysis framework for the ``tools/check_*.py`` lint suite.

Every lint in this repo has the same skeleton: walk a tree of ``.py``
files, parse each one (AST and/or tokenize), collect ``(path, line, what)``
violations, print them as ``path:line: what`` to stderr and exit nonzero.
Seven tools grew seven private copies of that skeleton; this package is the
single one they all share. See ``docs/static_analysis.md``.

Public surface (``from analysis import ...``):

* :data:`Violation` — the ``(path, line, what)`` tuple every lint emits.
* :func:`comment_lines` / :func:`marker_lines` — tokenize-based comment
  maps, the seam for per-site suppression markers (``# sync: ok``,
  ``# race: ok``, …).
* :func:`iter_py_files` / :func:`walk_sources` — tree walking with the
  canonical prune list and per-file SyntaxError→violation handling.
* :func:`report` — the shared ``main()`` tail: print violations, return
  the process exit code.
* :func:`repo_root` — the repo checkout containing this package.
* :func:`load_module_from_path` — importlib loader for checked-in
  registries (metrics, alerts, knobs) that must not import the package.
"""

from .framework import (
    Violation,
    comment_lines,
    iter_py_files,
    load_module_from_path,
    marker_lines,
    report,
    repo_root,
    walk_sources,
)

__all__ = [
    "Violation",
    "comment_lines",
    "iter_py_files",
    "load_module_from_path",
    "marker_lines",
    "report",
    "repo_root",
    "walk_sources",
]
