"""Core helpers shared by every ``tools/check_*.py`` lint.

Stdlib-only by construction: the lints run on the bare runtime image and
load checked-in registries (metrics, alerts, knobs) by path precisely so
they never import ``maggy_tpu`` (which would pull in jax).
"""

from __future__ import annotations

import importlib.util
import os
import re
import sys
import tokenize
from typing import Callable, Dict, Iterable, Iterator, List, NamedTuple, Set, Tuple, Union

#: Directory names pruned from every tree walk. ``.``-prefixed (VCS,
#: venvs), sphinx/mkdocs build output, and bytecode caches.
PRUNE_PREFIXES = (".", "_build", "__pycache__")


class Violation(NamedTuple):
    """One lint finding. A plain tuple subclass so existing self-tests that
    compare against ``(path, line, what)`` tuples keep passing."""

    path: str
    line: int
    what: str


def repo_root() -> str:
    """The repo checkout containing ``tools/analysis/``."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def comment_lines(source: str) -> Dict[int, str]:
    """line -> comment text, tolerating partial tokenization."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(iter(source.splitlines(True)).__next__):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return out


def marker_lines(
    comments: Union[str, Dict[int, str]], pattern: "re.Pattern[str]"
) -> Set[int]:
    """Line numbers whose comment matches ``pattern``.

    ``comments`` is either raw source (tokenized here) or a map already
    built by :func:`comment_lines` — lints matching several markers build
    the map once and call this per marker.
    """
    if isinstance(comments, str):
        comments = comment_lines(comments)
    return {ln for ln, text in comments.items() if pattern.search(text)}


def iter_py_files(roots: Union[str, Iterable[str]]) -> Iterator[str]:
    """Every ``.py`` file under ``roots`` (deterministic order).

    A root that is itself a file is yielded as-is (``bench.py`` in the
    chaos-kind lint); directories are walked with :data:`PRUNE_PREFIXES`
    applied at every level.
    """
    if isinstance(roots, str):
        roots = [roots]
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [
                d for d in dirnames if not d.startswith(PRUNE_PREFIXES)
            ]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def walk_sources(
    roots: Union[str, Iterable[str]],
    check: Callable[[str, str], List[Tuple[int, str]]],
    *,
    skip: Callable[[str], bool] = lambda path: False,
) -> List[Violation]:
    """Run ``check(source, path) -> [(line, what), ...]`` over every
    ``.py`` file under ``roots``.

    Unreadable files are skipped (tree races with editors/builds); a file
    that fails to parse is itself a violation so a syntax error can never
    silently shrink a lint's coverage.
    """
    violations: List[Violation] = []
    for path in iter_py_files(roots):
        if skip(path):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        try:
            hits = check(source, path)
        except SyntaxError as e:
            violations.append(Violation(path, e.lineno or 0, f"syntax error: {e.msg}"))
            continue
        violations.extend(Violation(path, line, what) for line, what in hits)
    return violations


def report(violations: Iterable[Tuple[str, int, str]], *, stream=None) -> int:
    """Print ``path:line: what`` per violation plus a count; return the
    process exit code (the shared tail of every lint's ``main``)."""
    stream = stream if stream is not None else sys.stderr
    violations = list(violations)
    for path, line, what in violations:
        print(f"{path}:{line}: {what}", file=stream)
    if violations:
        print(f"{len(violations)} violation(s)", file=stream)
        return 1
    return 0


def load_module_from_path(name: str, path: str):
    """Load a checked-in registry module by file path.

    No package import — registries (metrics, alerts, knobs) must stay
    stdlib-only so lints run on a bare interpreter. The module is placed
    in ``sys.modules`` first: dataclass processing resolves field types
    through ``sys.modules[cls.__module__]``.
    """
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod
