#!/usr/bin/env python
"""Lint: every ``docs/*.md`` page must appear in the mkdocs nav.

A page missing from ``mkdocs.yml``'s ``nav:`` builds fine but is
unreachable from the rendered site — docs rot silently (the exact failure
mode that orphaned earlier satellite pages). The nav is parsed with a
line regex rather than a YAML library so the lint runs on the bare runtime
image (pyyaml is not vendored).

Usage: ``python tools/check_docs_nav.py [repo_root]`` — exits nonzero
listing every orphaned page. Built on the shared ``tools/analysis``
framework (docs/static_analysis.md); wired into the tier-1 run via
``tests/test_telemetry.py`` alongside ``check_no_bare_print.py``.
"""

from __future__ import annotations

import os
import re
import sys

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import report, repo_root  # noqa: E402

# "  - Title: file.md" (any indent level, quoted or not)
_NAV_ENTRY = re.compile(r"^\s*-\s+(?:[^:]+:\s*)?['\"]?([\w./-]+\.md)['\"]?\s*$")


def nav_pages(mkdocs_yml: str):
    """Every .md path referenced from the nav section of mkdocs.yml."""
    pages = set()
    in_nav = False
    with open(mkdocs_yml, encoding="utf-8") as f:
        for line in f:
            stripped = line.rstrip("\n")
            if re.match(r"^nav\s*:", stripped):
                in_nav = True
                continue
            if in_nav:
                # nav block ends at the next top-level key
                if stripped and not stripped[0].isspace() and not stripped.startswith("-"):
                    break
                m = _NAV_ENTRY.match(stripped)
                if m:
                    pages.add(m.group(1))
    return pages


def orphaned_docs(repo: str):
    """docs/*.md files absent from the mkdocs nav."""
    mkdocs_yml = os.path.join(repo, "mkdocs.yml")
    docs_dir = os.path.join(repo, "docs")
    if not os.path.isfile(mkdocs_yml) or not os.path.isdir(docs_dir):
        return []
    pages = nav_pages(mkdocs_yml)
    missing = []
    for name in sorted(os.listdir(docs_dir)):
        if name.endswith(".md") and name not in pages:
            missing.append(os.path.join("docs", name))
    return missing


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    repo = args[0] if args else repo_root()
    violations = [
        (
            path,
            0,
            "not referenced from mkdocs.yml nav — add a nav entry or the "
            "page is unreachable from the docs site",
        )
        for path in orphaned_docs(repo)
    ]
    return report(violations)


if __name__ == "__main__":
    sys.exit(main())
