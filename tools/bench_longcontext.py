"""Long-context attention table on one chip (SURVEY §5.7 headline area).

BENCH_NOTES' only kernel table is S=2048 (round 1). This records, per
sequence length {2k, 4k, 8k, 16k}:

* fwd+bwd step time of the attention op — Pallas flash (auto tiles) vs the
  XLA blockwise schedule (dense fused is included at S<=4k where it fits);
* one FULL-model train step at S=8192 (bs=2, remat) — the "trains where
  dense cannot" claim with a measured tok/s number.

Prints one JSON line; the watchdog playbook runs it on tunnel recovery.

    python tools/bench_longcontext.py [--quick]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()

    from bench import ensure_live_backend

    cpu = ensure_live_backend()

    import jax
    import jax.numpy as jnp

    from maggy_tpu.models.transformer import default_attention
    from maggy_tpu.ops.attention import blockwise_attention
    from maggy_tpu.ops.flash import flash_attention

    quick = cpu or args.quick
    B, H, D = (1, 2, 128) if quick else (2, 8, 128)
    seqs = [256, 512] if quick else [2048, 4096, 8192, 16384]

    def timed_grad(fn, S):
        q = jax.random.normal(jax.random.key(1), (B, S, H, D), jnp.bfloat16)

        def loss(q, k, v):
            return (fn(q, k, v).astype(jnp.float32) ** 2).sum()

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        out = g(q, q, q)
        jax.block_until_ready(out)
        float(out[0].sum())  # host barrier (axon-safe)
        steps = 3 if quick else 10
        t0 = time.perf_counter()
        for _ in range(steps):
            out = g(q, q, q)
        float(out[0].sum())
        return (time.perf_counter() - t0) / steps * 1e3

    table = []
    for S in seqs:
        row = {"seq": S}
        row["flash_ms"] = round(
            timed_grad(lambda q, k, v: flash_attention(q, k, v, causal=True), S), 2
        )
        row["blockwise_ms"] = round(
            timed_grad(
                lambda q, k, v: blockwise_attention(q, k, v, causal=True), S
            ),
            2,
        )
        if S <= 4096:  # the [S,S] score matrix fits
            row["dense_ms"] = round(
                timed_grad(
                    lambda q, k, v: default_attention(q, k, v, causal=True), S
                ),
                2,
            )
        table.append(row)

    # full-model long-context train step: the single-chip "trains where the
    # dense score matrix cannot exist" datapoint
    model_row = None
    try:
        import optax

        from maggy_tpu.models import Decoder, DecoderConfig
        from maggy_tpu.train import TrainContext
        from maggy_tpu.train.data import synthetic_lm_batches

        if quick:
            cfg = DecoderConfig.tiny(max_seq_len=512)
            bs, S = 1, 512
        else:
            cfg = DecoderConfig(
                vocab_size=32_000, d_model=1024, n_layers=12, n_heads=8,
                n_kv_heads=8, d_ff=4096, max_seq_len=8192, remat=True,
            )
            bs, S = 2, 8192
        # one-device mesh: bs is tiny by design and must not need to divide
        # a CPU-fallback 8-device mesh
        ctx = TrainContext.create("dp", devices=jax.devices()[:1])
        trainer = ctx.trainer(Decoder(cfg), optax.adamw(1e-3))
        data = synthetic_lm_batches(cfg.vocab_size, bs, S, seed=0)
        state = trainer.make_state(jax.random.key(0), next(data))
        batch = trainer.shard_batch(next(data))
        state, m = trainer.step(state, batch)
        float(m["loss"])
        steps = 2 if quick else 5
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = trainer.step(state, batch)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / steps
        model_row = {
            "seq": S, "batch": bs, "step_ms": round(dt * 1e3, 1),
            "tok_per_sec": round(bs * S / dt, 1),
        }
    except Exception as e:  # noqa: BLE001 - the op table alone is still data
        model_row = {"error": f"{type(e).__name__}: {e}"}

    print(json.dumps({
        "metric": "longcontext_attention_table",
        "value": table[-1]["flash_ms"],
        "unit": "ms fwd+bwd at max S",
        "vs_baseline": None,
        "extra": {
            "cpu_fallback": cpu,
            "geometry": f"B={B} H={H} D={D}",
            "table": table,
            "model_step_s8k": model_row,
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
