#!/usr/bin/env python
"""Lint: ban host↔device synchronization inside annotated hot loops.

The overlap subsystem (docs/performance.md) only works while nothing in a
hot loop forces the XLA dispatch pipeline to drain: one stray ``float()`` on
a fresh device metric re-serializes host and device and silently costs the
whole prefetch/async-drain win. This lint makes that property durable:

* A ``for``/``while``/``def`` line carrying a ``# hot-loop`` comment marks
  its body as a device-hot region.
* Inside a region, calls that typically force a device→host sync are
  flagged: ``float(...)``, ``int(...)``, ``<x>.item()``, and
  ``np.asarray(...)`` / ``numpy.asarray(...)``.
* A deliberate, bounded sync (e.g. the lagged metrics drain reading a ref
  that is already ``window`` steps old, or compile-time measurement) is
  allowlisted per-site with a ``# sync: ok`` comment on any line the call
  spans — ideally with a reason after it.

The lint is syntactic, not type-aware: it flags ``int()`` of plain Python
values too. That is intentional — a hot loop should not need conversions at
all, and the annotation cost of a justified ``# sync: ok`` is one comment.

``REQUIRED_REGIONS`` pins the two loops the overlap PR rebuilt —
``Trainer.fit``'s step loop and ``Engine.step`` — so deleting the marker
(and with it the protection) is itself a violation.

Usage: ``python tools/check_host_sync.py [root]`` — exits nonzero listing
violations. Wired into the tier-1 run via ``tests/test_prefetch.py``,
beside the exception-hygiene, bare-print, and docs-nav lints.
"""

from __future__ import annotations

import ast
import os
import re
import sys
import tokenize
from typing import Dict, List, Set, Tuple

HOT_MARKER = re.compile(r"#\s*hot-loop")
OK_MARKER = re.compile(r"#\s*sync:\s*ok")

# (path suffix, function name) pairs that MUST contain a hot-loop region
REQUIRED_REGIONS: Tuple[Tuple[str, str], ...] = (
    (os.path.join("maggy_tpu", "train", "trainer.py"), "fit"),
    (os.path.join("maggy_tpu", "serve", "engine.py"), "step"),
)


def _comment_lines(source: str) -> Dict[int, str]:
    """line -> comment text, tolerating partial tokenization."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(iter(source.splitlines(True)).__next__):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return out


def _sync_call(node: ast.Call) -> str:
    """Name of the flagged sync pattern ``node`` matches, or ''."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in ("float", "int"):
        return f"{fn.id}()"
    if isinstance(fn, ast.Attribute):
        if fn.attr == "item":
            return ".item()"
        if fn.attr == "asarray" and isinstance(fn.value, ast.Name) and fn.value.id in (
            "np",
            "numpy",
        ):
            return f"{fn.value.id}.asarray()"
    return ""


def find_violations(source: str, path: str) -> List[Tuple[int, str]]:
    """(line, description) for every unjustified sync in a hot region."""
    tree = ast.parse(source, filename=path)
    comments = _comment_lines(source)
    hot_lines: Set[int] = {
        ln for ln, text in comments.items() if HOT_MARKER.search(text)
    }
    ok_lines: Set[int] = {
        ln for ln, text in comments.items() if OK_MARKER.search(text)
    }
    regions: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.For, ast.While, ast.FunctionDef, ast.AsyncFunctionDef)
        ) and node.lineno in hot_lines:
            regions.append((node.lineno, node.end_lineno or node.lineno))
    out: List[Tuple[int, str]] = []
    if not regions:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        what = _sync_call(node)
        if not what:
            continue
        if not any(lo <= node.lineno <= hi for lo, hi in regions):
            continue
        span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
        if any(ln in ok_lines for ln in span):
            continue
        out.append(
            (
                node.lineno,
                f"{what} inside a hot-loop region forces a host sync — "
                "move it out of the loop or justify with '# sync: ok'",
            )
        )
    return out


def has_hot_region(source: str, path: str, func_name: str) -> bool:
    """True when ``func_name`` in ``source`` contains a hot-loop marker."""
    tree = ast.parse(source, filename=path)
    comments = _comment_lines(source)
    hot_lines = {ln for ln, text in comments.items() if HOT_MARKER.search(text)}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == func_name:
            if any(node.lineno <= ln <= (node.end_lineno or node.lineno) for ln in hot_lines):
                return True
    return False


def check_tree(root: str) -> List[Tuple[str, int, str]]:
    violations: List[Tuple[str, int, str]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames if not d.startswith((".", "_build", "__pycache__"))
        ]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
            except OSError:
                continue
            try:
                hits = find_violations(source, path)
            except SyntaxError as e:
                violations.append((path, e.lineno or 0, f"syntax error: {e.msg}"))
                continue
            violations.extend((path, line, what) for line, what in hits)
            for suffix, func in REQUIRED_REGIONS:
                if path.endswith(suffix) and not has_hot_region(source, path, func):
                    violations.append(
                        (
                            path,
                            0,
                            f"required hot-loop marker missing from {func}() — "
                            "the overlap hot path lost its lint protection",
                        )
                    )
    return violations


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    root = args[0] if args else os.path.join(repo, "maggy_tpu")
    violations = check_tree(root)
    for path, line, what in violations:
        print(f"{path}:{line}: {what}", file=sys.stderr)
    if violations:
        print(f"{len(violations)} violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
