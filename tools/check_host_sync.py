#!/usr/bin/env python
"""Lint: ban host↔device synchronization inside annotated hot loops.

The overlap subsystem (docs/performance.md) only works while nothing in a
hot loop forces the XLA dispatch pipeline to drain: one stray ``float()`` on
a fresh device metric re-serializes host and device and silently costs the
whole prefetch/async-drain win. This lint makes that property durable:

* A ``for``/``while``/``def`` line carrying a ``# hot-loop`` comment marks
  its body as a device-hot region.
* Inside a region, calls that typically force a device→host sync are
  flagged: ``float(...)``, ``int(...)``, ``<x>.item()``, and
  ``np.asarray(...)`` / ``numpy.asarray(...)``.
* A deliberate, bounded sync (e.g. the lagged metrics drain reading a ref
  that is already ``window`` steps old, or compile-time measurement) is
  allowlisted per-site with a ``# sync: ok`` comment on any line the call
  spans — ideally with a reason after it.

The lint is syntactic, not type-aware: it flags ``int()`` of plain Python
values too. That is intentional — a hot loop should not need conversions at
all, and the annotation cost of a justified ``# sync: ok`` is one comment.

``REQUIRED_REGIONS`` pins the two loops the overlap PR rebuilt —
``Trainer.fit``'s step loop and ``Engine.step`` — so deleting the marker
(and with it the protection) is itself a violation.

Usage: ``python tools/check_host_sync.py [root]`` — exits nonzero listing
violations. Built on the shared ``tools/analysis`` framework
(docs/static_analysis.md); wired into the tier-1 run via
``tests/test_prefetch.py``, beside the exception-hygiene, bare-print, and
docs-nav lints.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Tuple

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import marker_lines, report, repo_root, walk_sources  # noqa: E402

HOT_MARKER = re.compile(r"#\s*hot-loop")
OK_MARKER = re.compile(r"#\s*sync:\s*ok")

# (path suffix, function name) pairs that MUST contain a hot-loop region
REQUIRED_REGIONS: Tuple[Tuple[str, str], ...] = (
    (os.path.join("maggy_tpu", "train", "trainer.py"), "fit"),
    (os.path.join("maggy_tpu", "serve", "engine.py"), "step"),
)


def _sync_call(node: ast.Call) -> str:
    """Name of the flagged sync pattern ``node`` matches, or ''."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in ("float", "int"):
        return f"{fn.id}()"
    if isinstance(fn, ast.Attribute):
        if fn.attr == "item":
            return ".item()"
        if fn.attr == "asarray" and isinstance(fn.value, ast.Name) and fn.value.id in (
            "np",
            "numpy",
        ):
            return f"{fn.value.id}.asarray()"
    return ""


def find_violations(source: str, path: str) -> List[Tuple[int, str]]:
    """(line, description) for every unjustified sync in a hot region."""
    tree = ast.parse(source, filename=path)
    hot_lines = marker_lines(source, HOT_MARKER)
    ok_lines = marker_lines(source, OK_MARKER)
    regions: List[Tuple[int, int]] = []
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.For, ast.While, ast.FunctionDef, ast.AsyncFunctionDef)
        ) and node.lineno in hot_lines:
            regions.append((node.lineno, node.end_lineno or node.lineno))
    out: List[Tuple[int, str]] = []
    if not regions:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        what = _sync_call(node)
        if not what:
            continue
        if not any(lo <= node.lineno <= hi for lo, hi in regions):
            continue
        span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
        if any(ln in ok_lines for ln in span):
            continue
        out.append(
            (
                node.lineno,
                f"{what} inside a hot-loop region forces a host sync — "
                "move it out of the loop or justify with '# sync: ok'",
            )
        )
    return out


def has_hot_region(source: str, path: str, func_name: str) -> bool:
    """True when ``func_name`` in ``source`` contains a hot-loop marker."""
    tree = ast.parse(source, filename=path)
    hot_lines = marker_lines(source, HOT_MARKER)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == func_name:
            if any(node.lineno <= ln <= (node.end_lineno or node.lineno) for ln in hot_lines):
                return True
    return False


def _check_file(source: str, path: str) -> List[Tuple[int, str]]:
    hits = find_violations(source, path)
    for suffix, func in REQUIRED_REGIONS:
        if path.endswith(suffix) and not has_hot_region(source, path, func):
            hits.append(
                (
                    0,
                    f"required hot-loop marker missing from {func}() — "
                    "the overlap hot path lost its lint protection",
                )
            )
    return hits


def check_tree(root: str) -> List[Tuple[str, int, str]]:
    return walk_sources(root, _check_file)


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.join(repo_root(), "maggy_tpu")
    return report(check_tree(root))


if __name__ == "__main__":
    sys.exit(main())
