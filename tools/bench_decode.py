"""Decode throughput microbench (VERDICT r3 item 7 'done' artifact).

Round-2 geometry for comparability (BENCH_NOTES): 267M decoder, B=8,
64-token prompt -> 512-token buffer, greedy. Measures generate (prefix
recompute) vs generate_cached (KV cache, now length-adaptive chunked reads)
and prints one JSON line. Target: >= 2x the recorded 3123 tok/s cached rate.

    python tools/bench_decode.py [--quick]
"""

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()

    from bench import ensure_live_backend  # repo root on sys.path (line 18)

    cpu_fallback = ensure_live_backend()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from maggy_tpu.models import Decoder, DecoderConfig
    from maggy_tpu.models.generate import generate, generate_cached

    if cpu_fallback or args.quick:
        cfg = DecoderConfig.tiny(max_seq_len=256)
        B, PROMPT, BUF = 2, 16, 128
    else:
        # the round-2 bench geometry (BENCH_NOTES decode table)
        cfg = DecoderConfig(
            vocab_size=32_000, d_model=1024, n_layers=12, n_heads=8,
            n_kv_heads=8, d_ff=4096, max_seq_len=1024,
        )
        B, PROMPT, BUF = 8, 64, 512

    model = Decoder(cfg)
    rng = np.random.default_rng(0)
    prompt = np.zeros((B, BUF), np.int32)
    prompt[:, :PROMPT] = rng.integers(1, cfg.vocab_size, (B, PROMPT))
    prompt = jnp.asarray(prompt)
    prompt_len = jnp.full((B,), PROMPT, jnp.int32)
    variables = model.init(jax.random.key(0), prompt[:, :8])
    decode_model = Decoder(dataclasses.replace(cfg, decode=True))

    def timed(fn, *a, **k):
        out = fn(*a, **k)
        jax.block_until_ready(out)
        float(out.sum())  # host-transfer barrier (axon-safe)
        t0 = time.perf_counter()
        out = fn(*a, **k)
        float(out.sum())
        dt = time.perf_counter() - t0
        new_tokens = B * (BUF - PROMPT)
        return new_tokens / dt, dt / (BUF - PROMPT) * 1e3

    cached_tps, cached_ms = timed(
        generate_cached, decode_model, variables["params"], prompt, prompt_len
    )
    recompute_tps, recompute_ms = timed(
        generate, model, variables, prompt, prompt_len
    )

    # one-pass prefill (r5): the whole prompt through the decode model in a
    # single apply vs PROMPT single-token applies (what generate_cached does)
    from maggy_tpu.models.generate import prefill

    pre_tokens = prompt[:, :PROMPT]
    pre_pos = jnp.broadcast_to(jnp.arange(PROMPT, dtype=jnp.int32), (B, PROMPT))
    # hoisted: a fresh jit-wrapped lambda per call would recompile every
    # time and the "timed" run would measure XLA compilation
    prefill_jit = jax.jit(
        lambda p: prefill(decode_model, variables["params"], p, pre_pos)[0]
    )

    def run_prefill():
        return prefill_jit(pre_tokens)

    out = run_prefill()
    jax.block_until_ready(out)
    float(out.sum())
    t0 = time.perf_counter()
    out = run_prefill()
    float(out.sum())
    prefill_tps = B * PROMPT / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": "decode_tok_per_sec_cached",
        "value": round(cached_tps, 1),
        "unit": "tok/s",
        # r2 record only comparable at the full geometry on silicon
        "vs_baseline": (
            round(cached_tps / 3123.0, 3)
            if not (cpu_fallback or args.quick)
            else None
        ),
        "extra": {
            "cpu_fallback": cpu_fallback,
            "cached_ms_per_token_batch": round(cached_ms, 3),
            "recompute_tok_per_sec": round(recompute_tps, 1),
            "prefill_tok_per_sec": round(prefill_tps, 1),
            "decode_chunk": cfg.decode_chunk,
            "geometry": f"B={B} prompt={PROMPT} buf={BUF} S={cfg.max_seq_len}",
            "device": str(jax.devices()[0]),
        },
    }))


if __name__ == "__main__":
    main()
