#!/usr/bin/env python
"""Lint: every chaos kind fired or scripted must be in the checked-in registry.

A typo'd chaos kind never errors at the seam — ``Chaos.fire("slice_dorp")``
simply never matches a rule, and ``MAGGY_TPU_CHAOS="slice_dorp:..."`` would
arm a fault that never fires — so a chaos acceptance test can silently stop
injecting anything and pass vacuously. This lint closes the kind set the
same way ``check_telemetry_names`` closes the metric set:

* ``maggy_tpu/resilience/chaos.py`` declares the registry: the ``KINDS``
  frozenset (``Chaos.parse`` also rejects unknown kinds at runtime; this
  tool catches the static sites, including ``.fire`` calls that bypass
  parse).
* This tool AST-walks ``maggy_tpu/``, ``tests/``, and ``bench.py`` for
  - ``.fire("kind", ...)`` calls on chaos-ish receivers (an identifier in
    the chain containing ``chaos``, or ``self``/``ch`` — the codebase's
    spellings), whose literal first argument must be a declared kind;
  - chaos *spec strings*: the literal argument of ``Chaos.parse(...)``,
    ``setenv("MAGGY_TPU_CHAOS", ...)``, ``environ["MAGGY_TPU_CHAOS"] = ...``
    assignments and ``{"MAGGY_TPU_CHAOS": ...}`` dict entries — every
    ``kind:`` head in the spec must be declared.
  Non-literal names/specs are skipped (statically uncheckable).

Usage: ``python tools/check_chaos_kinds.py [root ...]`` — exits nonzero
listing violations. Built on the shared ``tools/analysis`` framework
(docs/static_analysis.md); wired into tier-1 via
``tests/test_elastic_membership.py``, beside the telemetry-name,
host-sync, and exception-hygiene lints.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Optional, Set, Tuple

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import report, repo_root, walk_sources  # noqa: E402

ENV_VAR = "MAGGY_TPU_CHAOS"


def load_kinds(repo: str) -> Set[str]:
    """Extract the ``KINDS`` literal from chaos.py by AST (no package
    import — the lint must not pull jax into a bare interpreter)."""
    path = os.path.join(repo, "maggy_tpu", "resilience", "chaos.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "KINDS" for t in node.targets
        ):
            kinds = ast.literal_eval(
                node.value.args[0]
                if isinstance(node.value, ast.Call) and node.value.args
                else node.value
            )
            return set(kinds)
    raise RuntimeError(f"no KINDS registry found in {path}")


def _spec_kinds(spec: str) -> List[str]:
    """The ``kind`` heads of a chaos spec string (same split as
    ``Chaos.parse``, minus validation)."""
    out = []
    for rule in spec.split(";"):
        rule = rule.strip()
        if rule:
            out.append(rule.partition(":")[0].strip())
    return out


def _chain_names(expr: ast.AST) -> List[str]:
    names = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return names


def _receiver_is_chaos(expr: ast.AST) -> bool:
    return any(
        "chaos" in n.lower() or n in ("self", "ch") for n in _chain_names(expr)
    )


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def check_source(source: str, path: str, kinds: Set[str]) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    tree = ast.parse(source, filename=path)

    def bad_spec(node: ast.AST, spec: str, where: str) -> None:
        for k in _spec_kinds(spec):
            if k not in kinds:
                out.append(
                    (
                        node.lineno,
                        f"{where}: unknown chaos kind {k!r} — declare it in "
                        "resilience/chaos.py KINDS or fix the typo",
                    )
                )

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            fn = node.func
            if fn.attr == "fire" and node.args and _receiver_is_chaos(fn.value):
                name = _literal_str(node.args[0])
                if name is not None and name not in kinds:
                    out.append(
                        (
                            node.lineno,
                            f"fire({name!r}) is not a declared chaos kind — "
                            "add it to resilience/chaos.py KINDS",
                        )
                    )
            elif fn.attr == "parse" and node.args and any(
                "Chaos" in n for n in _chain_names(fn.value)
            ):
                spec = _literal_str(node.args[0])
                if spec is not None:
                    bad_spec(node, spec, "Chaos.parse")
            elif fn.attr in ("setenv", "setdefault") and len(node.args) >= 2:
                if _literal_str(node.args[0]) == ENV_VAR:
                    spec = _literal_str(node.args[1])
                    if spec is not None:
                        bad_spec(node, spec, ENV_VAR)
        elif isinstance(node, ast.Assign):
            # os.environ["MAGGY_TPU_CHAOS"] = "<spec>"
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and _literal_str(tgt.slice) == ENV_VAR
                ):
                    spec = _literal_str(node.value)
                    if spec is not None:
                        bad_spec(node, spec, ENV_VAR)
        elif isinstance(node, ast.Dict):
            # {"MAGGY_TPU_CHAOS": "<spec>"} env dicts (subprocess launches)
            for key, val in zip(node.keys, node.values):
                if key is not None and _literal_str(key) == ENV_VAR:
                    spec = _literal_str(val)
                    if spec is not None:
                        bad_spec(node, spec, ENV_VAR)
    return out


def check_tree(roots: List[str], kinds: Set[str]) -> List[Tuple[str, int, str]]:
    return walk_sources(
        roots, lambda source, path: check_source(source, path, kinds)
    )


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    repo = repo_root()
    roots = args or [
        os.path.join(repo, "maggy_tpu"),
        os.path.join(repo, "tests"),
        os.path.join(repo, "bench.py"),
    ]
    kinds = load_kinds(repo)
    return report(check_tree(roots, kinds))


if __name__ == "__main__":
    sys.exit(main())
