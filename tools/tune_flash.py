"""Flash-attention tile sweep (fwd AND bwd grids) on real hardware.

Round-2 found 512-row forward q tiles ~2.7x faster than the conventional 128
(BENCH_NOTES); the backward kernels were left on the forward's tiles
(VERDICT r3 weak 1). This sweeps bwd_block_q/bwd_block_k independently on
the bench geometry and prints a ranked table — run it when the tunnel is
alive, then bake the winner into _auto_blocks' backward variant.

The tile grid is the autopilot knob registry's ``FLASH_TILE_CHOICES``
(maggy_tpu/autopilot/knobs.py) — the manual sweep and the Planner's
compute-bound recommendations draw candidates from the same table, so a
tile this tool can measure is always one the autopilot may legally plan.

    python tools/tune_flash.py [--seq 1024] [--steps 10]
"""

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.util import pin_cpu_if_requested

pin_cpu_if_requested()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq", type=int, default=1024)
    parser.add_argument("--steps", type=int, default=10)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument(
        "--emit", default=None, metavar="PATH",
        help="write the winning bwd tiles as JSON (consumed by bench.py via "
             "MAGGY_TPU_FLASH_BWD_Q/_K; see tools/tpu_playbook.py)",
    )
    args = parser.parse_args()

    from bench import ensure_live_backend

    cpu = ensure_live_backend()

    import jax
    import jax.numpy as jnp

    from maggy_tpu.ops.flash import flash_attention

    # bench-geometry attention shape: d_model 1024, 8 heads -> head_dim 128
    B, S, H, D = (2, 256, 2, 128) if (cpu or args.quick) else (16, args.seq, 8, 128)
    q = jax.random.normal(jax.random.key(1), (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(2), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(3), (B, S, H, D), jnp.bfloat16)

    from maggy_tpu.autopilot.knobs import FLASH_TILE_CHOICES

    cands = [c for c in FLASH_TILE_CHOICES if c <= S] or [S]
    if cpu or args.quick:
        cands = cands[:2]

    def time_one(bq, bk, bbq, bbk):
        def loss(q, k, v):
            o = flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk,
                bwd_block_q=bbq, bwd_block_k=bbk,
            )
            return (o.astype(jnp.float32) ** 2).sum()

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        out = g(q, k, v)
        jax.block_until_ready(out)
        float(out[0].sum())  # host barrier
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = g(q, k, v)
        float(out[0].sum())
        return (time.perf_counter() - t0) / args.steps * 1e3

    rows = []
    fwd_best = (512 if 512 in cands else cands[-1], 512 if 512 in cands else cands[-1])
    for bbq, bbk in itertools.product(cands, cands):
        try:
            ms = time_one(fwd_best[0], fwd_best[1], bbq, bbk)
            rows.append({"bwd_block_q": bbq, "bwd_block_k": bbk, "ms": round(ms, 3)})
            print(f"bwd ({bbq:4d},{bbk:4d}): {ms:8.3f} ms")
        except Exception as e:  # noqa: BLE001 - a tile that fails to lower is data
            print(f"bwd ({bbq:4d},{bbk:4d}): FAILED {type(e).__name__}")
    rows.sort(key=lambda r: r["ms"])
    print(json.dumps({
        "geometry": f"B={B} S={S} H={H} D={D}",
        "fwd_tiles": fwd_best,
        "ranking": rows[:5],
        "device": str(jax.devices()[0]),
    }))
    # never emit toy-geometry (cpu/--quick) tiles as flagship winners
    if args.emit and rows and not cpu and not args.quick:
        with open(args.emit, "w") as f:
            json.dump({
                "bwd_block_q": rows[0]["bwd_block_q"],
                "bwd_block_k": rows[0]["bwd_block_k"],
                "ms": rows[0]["ms"],
                "geometry": f"B={B} S={S} H={H} D={D}",
                "device": str(jax.devices()[0]),
            }, f)


if __name__ == "__main__":
    main()
