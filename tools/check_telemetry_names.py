#!/usr/bin/env python
"""Lint: every telemetry metric name must be in the checked-in registry.

A typo'd metric name (``tel.gauge("serve.ttft_m", ...)``) never errors — it
silently mints a second series, and every consumer keyed on the real name
(monitor panel, SSTATS percentile, analyze_trace attribution) quietly loses
data. This lint makes the name set closed:

* ``maggy_tpu/telemetry/metrics.py`` is the registry — per-kind frozensets
  (``GAUGES``/``COUNTERS``/``HISTOGRAMS``/``EVENTS``) plus
  ``DYNAMIC_PREFIXES`` for the few f-string names whose tail is a bounded
  runtime enum (request terminal states, RPC verbs).
* This tool AST-walks ``maggy_tpu/`` for ``.gauge(`` / ``.count(`` /
  ``.histogram(`` / ``.event(`` calls on telemetry-ish receivers (any name
  in the receiver chain containing ``tel`` — ``tel``, ``telemetry``,
  ``self.telemetry``, ``telemetry.get()`` — so ``str.count`` is never
  flagged) and checks:
  - a literal string name must be in the registry set for its kind;
  - an f-string name's leading literal must match a dynamic prefix;
  - anything else (a plain variable) is skipped — it cannot be checked
    statically, and the codebase passes literals everywhere that matters.
* Every registered name must carry a unit (``metrics.UNITS``, values from
  ``metrics.VALID_UNITS``) — so consumers (monitor, metrics_query, docs)
  never guess at scaling.
* ``maggy_tpu/telemetry/alerts.py`` is loaded the same way and its rule
  registry validated: unique ``alert.``-prefixed names, known
  kind/severity/scope, referenced metrics registered. Any *other*
  ``"alert.*"`` string literal in the tree must name a registered rule or
  transition event — a typo'd rule name must not mint a phantom alert.

Usage: ``python tools/check_telemetry_names.py [root]`` — exits nonzero
listing violations. Built on the shared ``tools/analysis`` framework
(docs/static_analysis.md); wired into the tier-1 run via
``tests/test_tracing.py``, beside the host-sync, exception-hygiene,
bare-print, and docs-nav lints.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import (  # noqa: E402
    load_module_from_path,
    report,
    repo_root,
    walk_sources,
)

TELEMETRY_METHODS = ("gauge", "count", "histogram", "event")


def load_registry(repo: str):
    """Load metrics.py by path (no package import — it must stay stdlib-only)."""
    return load_module_from_path(
        "maggy_tpu_metrics_registry",
        os.path.join(repo, "maggy_tpu", "telemetry", "metrics.py"),
    )


def load_alerts(repo: str):
    """Load the alert-rule registry by path (stdlib-only, like metrics.py)."""
    return load_module_from_path(
        "maggy_tpu_alerts_registry",
        os.path.join(repo, "maggy_tpu", "telemetry", "alerts.py"),
    )


def check_units(registry) -> List[str]:
    """Every registered name carries a known unit; no stale unit entries."""
    out: List[str] = []
    units = getattr(registry, "UNITS", None)
    valid = getattr(registry, "VALID_UNITS", None)
    if units is None or valid is None:
        return ["metrics.py must define UNITS and VALID_UNITS"]
    for name in sorted(registry.ALL):
        unit = units.get(name)
        if unit is None:
            out.append(f"{name}: no unit — add it to UNITS in telemetry/metrics.py")
        elif unit not in valid:
            out.append(f"{name}: unknown unit {unit!r} (valid: {sorted(valid)})")
    for name in sorted(units):
        if name not in registry.ALL:
            out.append(f"UNITS entry {name!r} is not a registered metric")
    return out


def check_alert_registry(alerts, registry) -> List[str]:
    """Structural validation of the checked-in alert rules."""
    out: List[str] = []
    rules = getattr(alerts, "RULES", ())
    if len({r.name for r in rules}) != len(rules):
        out.append("duplicate rule names in alerts.RULES")
    for r in rules:
        where = f"alerts.RULES[{r.name!r}]"
        if not r.name.startswith("alert."):
            out.append(f"{where}: name must start with 'alert.'")
        if r.kind not in alerts.KINDS:
            out.append(f"{where}: unknown kind {r.kind!r}")
        if r.severity not in alerts.SEVERITIES:
            out.append(f"{where}: unknown severity {r.severity!r}")
        if r.scope not in alerts.SCOPES:
            out.append(f"{where}: unknown scope {r.scope!r}")
        if not r.summary:
            out.append(f"{where}: empty summary")
        if r.kind == "threshold" and not r.metric:
            out.append(f"{where}: threshold rule needs a metric")
        if r.kind == "burn_rate":
            counter_pair = bool(r.ok_metric) and bool(r.miss_metric)
            hist_src = bool(r.metric) and r.slo_ms is not None
            if not (counter_pair or hist_src):
                out.append(
                    f"{where}: burn_rate rule needs ok/miss counters or metric+slo_ms"
                )
            if not r.windows:
                out.append(f"{where}: burn_rate rule needs windows")
            if not 0.0 < r.objective < 1.0:
                out.append(f"{where}: objective must be in (0, 1)")
        for m in r.metrics():
            if m not in registry.ALL and not any(
                m.startswith(p) for p in registry.DYNAMIC_PREFIXES
            ):
                out.append(f"{where}: references unregistered metric {m!r}")
    for ev in (alerts.ALERT_FIRING, alerts.ALERT_RESOLVED):
        if ev not in registry.EVENTS:
            out.append(f"transition event {ev!r} missing from metrics.EVENTS")
    return out


# the capacity-alerting contract (docs/observability.md "Capacity"): these
# rules must exist with exactly these series wirings. They are profcap's
# default watch list — deleting or re-pointing one silently disarms
# alert-triggered profile capture, so the wiring is pinned here.
CAPACITY_RULES = {
    "alert.hbm_headroom": {
        "kind": "burn_rate",
        "ok_metric": "mem.headroom_ok",
        "miss_metric": "mem.headroom_miss",
    },
    "alert.fragmentation": {
        "kind": "threshold",
        "metric": "serve.fragmentation",
    },
}


def check_capacity_rules(alerts) -> List[str]:
    """The two capacity rules exist and read the series the exporters
    actually write (MemoryLedger's counter pair, the scheduler tick's
    fragmentation gauge)."""
    out: List[str] = []
    by_name = {r.name: r for r in getattr(alerts, "RULES", ())}
    for name, want in CAPACITY_RULES.items():
        r = by_name.get(name)
        if r is None:
            out.append(f"capacity rule {name!r} missing from alerts.RULES")
            continue
        for field, expect in want.items():
            got = getattr(r, field, None)
            if got != expect:
                out.append(
                    f"alerts.RULES[{name!r}]: {field}={got!r}, expected {expect!r}"
                )
        if want.get("kind") == "burn_rate" and len(getattr(r, "windows", ()) or ()) < 2:
            out.append(
                f"alerts.RULES[{name!r}]: multi-window burn rule needs >= 2 windows"
            )
    return out


# the host-DRAM tier observability contract (docs/serving.md "Host-DRAM
# page tier"): the tier.* series the scheduler tick and engine emit must
# stay registered under exactly these kinds with these units — consumers
# (monitor tier line, bench extra.fleetkv, fleet capacity aggregation) key
# on them, and a silent re-kind (gauge -> counter) breaks every one.
TIER_SERIES = {
    "tier.host_pages_free": ("gauge", "count"),
    "tier.host_pages_total": ("gauge", "count"),
    "tier.host_bytes": ("gauge", "bytes"),
    "tier.resident_packs": ("gauge", "count"),
    "tier.spills": ("count", "count"),
    "tier.fills": ("count", "count"),
    "tier.spilled_pages": ("count", "count"),
    "tier.filled_pages": ("count", "count"),
    "tier.prefix_spills": ("count", "count"),
    "tier.prefix_fills": ("count", "count"),
    "tier.host_evictions": ("count", "count"),
    "tier.pressure_spills": ("count", "count"),
    "tier.affinity_hits": ("count", "count"),
    "tier.affinity_misses": ("count", "count"),
    "tier.swap_in_ms": ("histogram", "ms"),
    "tier.spill_ms": ("histogram", "ms"),
}


def check_tier_series(registry) -> List[str]:
    """Every pinned tier.* series is registered under the expected kind
    and carries the expected unit."""
    out: List[str] = []
    units = getattr(registry, "UNITS", {})
    for name, (kind, unit) in sorted(TIER_SERIES.items()):
        allowed = registry.BY_KIND.get(kind, frozenset())
        if name not in allowed:
            out.append(
                f"tier series {name!r} must be registered as a {kind} "
                "in telemetry/metrics.py"
            )
            continue
        got = units.get(name)
        if got != unit:
            out.append(
                f"tier series {name!r}: unit {got!r}, expected {unit!r}"
            )
    return out


# the autoscaler observability contract (docs/fleet.md "Autoscaling"): the
# fleet.* capacity-loop series must stay registered under exactly these
# kinds with these units — the bench autoscale gate, the monitor autoscale
# line, and alert.fleet_at_capacity all key on them.
AUTOSCALE_SERIES = {
    "fleet.replicas": ("gauge", "count"),
    "fleet.draining": ("gauge", "count"),
    "fleet.at_capacity": ("gauge", "count"),
    "fleet.scale_events": ("count", "count"),
    "fleet.drain_ms": ("histogram", "ms"),
}

# the autoscaler's decision journal: every fleet.scale.* milestone must be
# a registered event — tests and ops tooling replay scale decisions from
# these names, so the set is pinned closed here
AUTOSCALE_EVENTS = (
    "fleet.scale.up",
    "fleet.scale.down",
    "fleet.scale.admitted",
    "fleet.scale.retired",
    "fleet.scale.committed",
    "fleet.scale.rollback",
    "fleet.scale.guard_extended",
    "fleet.scale.blocked",
)


def check_autoscale_series(registry, alerts) -> List[str]:
    """Every pinned autoscale series/event is registered under the
    expected kind, and the at-capacity alert reads the pinned gauge."""
    out: List[str] = []
    units = getattr(registry, "UNITS", {})
    for name, (kind, unit) in sorted(AUTOSCALE_SERIES.items()):
        allowed = registry.BY_KIND.get(kind, frozenset())
        if name not in allowed:
            out.append(
                f"autoscale series {name!r} must be registered as a {kind} "
                "in telemetry/metrics.py"
            )
            continue
        got = units.get(name)
        if got != unit:
            out.append(
                f"autoscale series {name!r}: unit {got!r}, expected {unit!r}"
            )
    for name in AUTOSCALE_EVENTS:
        if name not in registry.EVENTS:
            out.append(
                f"autoscale journal event {name!r} missing from metrics.EVENTS"
            )
    rule = {r.name: r for r in getattr(alerts, "RULES", ())}.get(
        "alert.fleet_at_capacity"
    )
    if rule is None:
        out.append("rule 'alert.fleet_at_capacity' missing from alerts.RULES")
    elif rule.kind != "threshold" or rule.metric != "fleet.at_capacity":
        out.append(
            "alerts.RULES['alert.fleet_at_capacity'] must be a threshold "
            "rule over the 'fleet.at_capacity' gauge"
        )
    return out


def _receiver_is_telemetry(expr: ast.AST) -> bool:
    """True when the call receiver plausibly is a telemetry recorder: some
    identifier in its chain contains 'tel'. Keeps ``"abc".count("a")`` and
    ``mylist.count(x)`` out of scope."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and "tel" in node.id.lower():
            return True
        if isinstance(node, ast.Attribute) and "tel" in node.attr.lower():
            return True
    return False


def check_source(source: str, path: str, registry, alert_names=None) -> List[Tuple[int, str]]:
    out: List[Tuple[int, str]] = []
    tree = ast.parse(source, filename=path)
    for node in ast.walk(tree):
        if (
            alert_names is not None
            and isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("alert.")
            and node.value != "alert."  # the bare prefix (strip/match code)
            and node.value not in alert_names
        ):
            out.append(
                (
                    node.lineno,
                    f"{node.value!r} is not a registered alert rule or "
                    "transition event — add it to telemetry/alerts.py RULES "
                    "or fix the typo",
                )
            )
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr not in TELEMETRY_METHODS:
            continue
        if not _receiver_is_telemetry(fn.value):
            continue
        if not node.args:
            continue
        arg = node.args[0]
        allowed = registry.BY_KIND[fn.attr]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            name = arg.value
            if name not in allowed:
                hint = (
                    " (registered under another kind)"
                    if name in registry.ALL
                    else ""
                )
                out.append(
                    (
                        node.lineno,
                        f"{fn.attr}({name!r}) not in the metric registry"
                        f"{hint} — add it to telemetry/metrics.py or fix the typo",
                    )
                )
        elif isinstance(arg, ast.JoinedStr):
            lead = ""
            if arg.values and isinstance(arg.values[0], ast.Constant):
                lead = str(arg.values[0].value)
            if not any(lead.startswith(p) for p in registry.DYNAMIC_PREFIXES):
                out.append(
                    (
                        node.lineno,
                        f"{fn.attr}(f\"{lead}...\") has no registered dynamic "
                        "prefix — add one to DYNAMIC_PREFIXES or use a literal",
                    )
                )
        # plain variables: statically uncheckable, skipped
    return out


def check_tree(root: str, registry, alert_names=None) -> List[Tuple[str, int, str]]:
    return walk_sources(
        root, lambda source, path: check_source(source, path, registry, alert_names)
    )


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    repo = repo_root()
    root = args[0] if args else os.path.join(repo, "maggy_tpu")
    registry = load_registry(repo)
    alerts = load_alerts(repo)
    violations: List[Tuple[str, int, str]] = []
    reg_path = os.path.join(repo, "maggy_tpu", "telemetry", "metrics.py")
    violations.extend((reg_path, 0, what) for what in check_units(registry))
    violations.extend(
        (reg_path, 0, what) for what in check_tier_series(registry)
    )
    alerts_path = os.path.join(repo, "maggy_tpu", "telemetry", "alerts.py")
    violations.extend(
        (alerts_path, 0, what) for what in check_alert_registry(alerts, registry)
    )
    violations.extend(
        (alerts_path, 0, what) for what in check_capacity_rules(alerts)
    )
    violations.extend(
        (reg_path, 0, what)
        for what in check_autoscale_series(registry, alerts)
    )
    alert_names = {r.name for r in alerts.RULES} | {
        alerts.ALERT_FIRING,
        alerts.ALERT_RESOLVED,
    }
    violations.extend(check_tree(root, registry, alert_names))
    return report(violations)


if __name__ == "__main__":
    sys.exit(main())
