#!/usr/bin/env python
"""Query exported time-series snapshots from the command line.

Works on any JSON a ``METRICS`` RPC verb (or ``SeriesStore.snapshot()``)
produced — a worker's store, the router's fleet-aggregate store, or the
router's full reply carrying per-replica stores. Windowed queries run the
SAME code as the live system (:mod:`maggy_tpu.telemetry.timeseries`), so a
percentile computed here over exported per-replica snapshots reproduces the
router's fleet-merged number exactly (bucket addition commutes with the
windowed subtraction when ticks align — which the router's single-timestamp
sampling guarantees).

Usage::

    python tools/metrics_query.py SNAP.json --list
    python tools/metrics_query.py SNAP.json --name serve.ttft_ms --q 0.95 --window 30
    python tools/metrics_query.py SNAP.json --name serve.slo_miss --rate --window 30
    python tools/metrics_query.py --merge R0.json R1.json --name serve.ttft_ms --q 0.95 --window 30

``SNAP.json`` may be a bare store snapshot (``{"v": 1, "series": ...}``), a
METRICS reply (``{"metrics": ..., "replicas": ...}``), or — with
``--replica N`` — one replica's store out of a fleet reply. Everything
prints as one JSON object per invocation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from maggy_tpu.telemetry.timeseries import (  # noqa: E402
    SeriesStore,
    merge_windowed_percentile,
)


def load_store(path: str, replica: Optional[str] = None) -> SeriesStore:
    """Load one store from a snapshot file, unwrapping METRICS replies."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if replica is not None:
        replicas = doc.get("replicas") or {}
        if str(replica) not in replicas:
            raise KeyError(
                f"{path}: no replica {replica!r} (have {sorted(replicas)})"
            )
        doc = replicas[str(replica)]
    elif "series" not in doc and isinstance(doc.get("metrics"), dict):
        doc = doc["metrics"]
    return SeriesStore.from_snapshot(doc)


def query(
    stores: List[SeriesStore],
    name: str,
    window_s: float,
    q: Optional[float] = None,
    rate: bool = False,
    now: Optional[float] = None,
) -> dict:
    """One windowed query over one or many stores (many = fleet merge)."""
    out: dict = {"name": name, "window_s": window_s}
    if len(stores) > 1:
        out["merged_from"] = len(stores)
        if q is not None:
            out[f"p{int(q * 100)}"] = merge_windowed_percentile(
                stores, name, q, window_s, now
            )
            return out
        # gauge/counter fleet merge — the offline reproduction of the
        # router's capacity aggregation: gauges sum latest values (and
        # report min/max, so headroom-style "tightest replica" reads are
        # one invocation), counters sum windowed deltas/rates
        series = [s.get(name) for s in stores]
        series = [s for s in series if s is not None]
        if not series:
            raise SystemExit(f"no series {name!r} in any snapshot (try --list)")
        kind = series[0].kind
        out["kind"] = kind
        if kind == "hist":
            raise SystemExit("--merge on a hist series requires --q")
        if kind == "counter" or rate:
            deltas = [s.delta(window_s, now) for s in series]
            deltas = [d for d in deltas if d is not None]
            out["delta"] = sum(deltas) if deltas else None
            rates = [s.rate(window_s, now) for s in series]
            rates = [r for r in rates if r is not None]
            out["rate_per_s"] = sum(rates) if rates else None
        else:
            latests = [s.latest() for s in series]
            vals = [v for v in (lt[1] for lt in latests if lt is not None)]
            out["sum"] = sum(vals) if vals else None
            out["min"] = min(vals) if vals else None
            out["max"] = max(vals) if vals else None
        return out
    s = stores[0].get(name)
    if s is None:
        raise SystemExit(f"no series {name!r} (try --list)")
    out["kind"] = s.kind
    out["points"] = len(s)
    latest = s.latest()
    if latest is not None and s.kind != "hist":
        out["latest"] = latest[1]
    if s.kind == "hist":
        for qq in ((q,) if q is not None else (0.5, 0.95, 0.99)):
            out[f"p{int(qq * 100)}"] = s.percentile(qq, window_s, now)
    elif rate or s.kind == "counter":
        out["delta"] = s.delta(window_s, now)
        out["rate_per_s"] = s.rate(window_s, now)
    return out


def list_series(store: SeriesStore) -> dict:
    return {
        "series": [
            {"name": name, "kind": store.get(name).kind, "points": len(store.get(name))}
            for name in store.names()
        ]
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("snapshot", nargs="?", help="snapshot JSON (store or METRICS reply)")
    p.add_argument("--merge", nargs="+", metavar="SNAP",
                   help="merge these per-replica snapshots (fleet percentile)")
    p.add_argument("--replica", help="pick one replica store out of a fleet reply")
    p.add_argument("--list", action="store_true", help="list series and exit")
    p.add_argument("--name", help="series to query")
    p.add_argument("--window", type=float, default=60.0, help="window seconds")
    p.add_argument("--q", type=float, help="percentile (0..1) for hist series")
    p.add_argument("--rate", action="store_true", help="per-second rate over the window")
    p.add_argument("--now", type=float, help="window end (default: newest point)")
    args = p.parse_args(argv)

    if args.merge:
        stores = [load_store(path, args.replica) for path in args.merge]
    elif args.snapshot:
        stores = [load_store(args.snapshot, args.replica)]
    else:
        p.error("need a snapshot file or --merge")
    if args.list:
        print(json.dumps(list_series(stores[0]), indent=2))
        return 0
    if not args.name:
        p.error("--name required unless --list")
    result = query(
        stores, args.name, args.window, q=args.q, rate=args.rate, now=args.now
    )
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
