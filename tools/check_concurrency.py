#!/usr/bin/env python
"""Lint: lock-discipline race detector for the threaded runtime.

The serving fleet is a web of threads — router pump, scheduler loop,
prefetcher, telemetry sink/flight-recorder, membership monitor, driver
heartbeats — and every past race (torn ``Scheduler.stats()``, sink
rotate-vs-append) was found by accident. This analyzer turns the locking
conventions into checked invariants. It builds a per-class concurrency
model from the AST:

* **lock attributes** — ``self.X = threading.Lock()/RLock()/Condition()``
  (a ``Condition(self.Y)`` shares ``Y``'s lock identity);
* **thread entry points** — methods passed as ``Thread(target=self.X)``,
  methods called from a module-level function that is itself a thread
  target, daemon-loop methods (``*_loop``), and methods carrying a
  ``# thread-entry`` marker (called directly from a foreign thread);
* **lock regions** — ``with self.X:`` spans plus whole methods whose
  ``def`` line carries ``# guarded-by: <lock>`` (caller holds the lock);
* **attribute reads/writes** — every ``self.attr`` access with the lock
  set held at that site.

Three checks run over the model:

1. **unguarded shared state** — an attribute written from a thread entry
   point and touched from any other method must be accessed under a class
   lock at every site, or be declared ``# guarded-by: <lock>`` on its
   ``__init__`` assignment (a non-lock guard name documents an external
   mechanism, e.g. ``queue-internal``), or be suppressed per-site or
   per-attribute with a justified ``# race: ok — <reason>``.
2. **lock-order inversion** — the cross-class lock-acquisition graph
   (lexical nesting plus calls into lock-taking methods, receiver resolved
   by name hint the way ``check_telemetry_names`` resolves telemetry
   receivers) must stay acyclic. The serving hierarchy is
   router → replica → scheduler → recorder. ``# lock-order: ok — <reason>``
   drops a deliberate edge.
3. **blocking-under-lock** — RPC requests, socket/frame I/O, ``sleep``,
   thread ``join`` and ``jax.block_until_ready``/``device_get`` while
   holding a lock are flagged; ``# blocking: ok — <reason>`` allowlists a
   bounded, deliberate case (the router invariant "the pump thread owns
   all downstream sockets, handlers stay lock-only" is machine-checked by
   this rule).

Every suppression must carry a reason — a bare marker is itself a
violation. ``REQUIRED_MODELS`` pins the core threaded classes so deleting
a lock (and with it the model) is a violation, mirroring
``check_host_sync.REQUIRED_REGIONS``.

Usage: ``python tools/check_concurrency.py [root]`` — exits nonzero
listing violations. Built on the shared ``tools/analysis`` framework
(docs/static_analysis.md); wired into the tier-1 run via
``tests/test_concurrency_lint.py``. The runtime counterpart is
``maggy_tpu/core/lockdebug.py`` (``MAGGY_TPU_LOCK_ORDER=1``), which
asserts the same acyclicity on live acquisitions.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
if _TOOLS_DIR not in sys.path:
    sys.path.insert(0, _TOOLS_DIR)

from analysis import Violation, comment_lines, iter_py_files, report, repo_root  # noqa: E402

GUARDED_BY = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
RACE_OK = re.compile(r"#\s*race:\s*ok\b\s*(.*)")
LOCK_ORDER_OK = re.compile(r"#\s*lock-order:\s*ok\b\s*(.*)")
BLOCKING_OK = re.compile(r"#\s*blocking:\s*ok\b\s*(.*)")
THREAD_ENTRY = re.compile(r"#\s*thread-entry\b")

LOCK_FACTORIES = ("Lock", "RLock", "Condition", "lock", "rlock", "condition")
LOCKISH = ("lock", "mutex", "cond")
CONSTRUCTORS = ("__init__", "__post_init__", "__del__")

# (path suffix, class name, lock attribute): the class must exist with that
# lock and at least one thread entry point — deleting the lock (or the
# model) is itself a violation, mirroring check_host_sync.REQUIRED_REGIONS.
REQUIRED_MODELS: Tuple[Tuple[str, str, str], ...] = (
    (os.path.join("maggy_tpu", "serve", "scheduler.py"), "Scheduler", "_lock"),
    (os.path.join("maggy_tpu", "serve", "fleet", "router.py"), "Router", "_lock"),
    (os.path.join("maggy_tpu", "serve", "fleet", "replica.py"), "CircuitBreaker", "_lock"),
    (os.path.join("maggy_tpu", "serve", "qos.py"), "QuotaLedger", "_lock"),
    (os.path.join("maggy_tpu", "serve", "loadgen.py"), "TrafficReplay", "_lock"),
    (os.path.join("maggy_tpu", "telemetry", "flightrec.py"), "Watchdog", "_lock"),
    (os.path.join("maggy_tpu", "telemetry", "memtrack.py"), "MemoryLedger", "_lock"),
    (os.path.join("maggy_tpu", "telemetry", "profcap.py"), "ProfileCapture", "_lock"),
    (os.path.join("maggy_tpu", "core", "driver", "base.py"), "Driver", "lock"),
    (os.path.join("maggy_tpu", "serve", "tier", "host_pool.py"), "HostPagePool", "_lock"),
    (os.path.join("maggy_tpu", "serve", "tier", "tiering.py"), "TieringPolicy", "_lock"),
    (os.path.join("maggy_tpu", "serve", "tier", "prefixmap.py"), "FleetPrefixMap", "_lock"),
    (os.path.join("maggy_tpu", "serve", "fleet", "autoscale.py"), "Autoscaler", "_lock"),
)


def _strip_reason(text: str) -> str:
    """The justification after a suppression marker, sans separators."""
    return text.lstrip(" \t—–:-").strip()


def _chain(expr: ast.AST) -> List[str]:
    """Identifiers in an attribute chain, outermost first (``a.b.c`` →
    ``['a', 'b', 'c']``); empty for non-chain expressions."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _final_name(expr: ast.AST) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _is_lockish(name: str) -> bool:
    low = name.lower()
    return any(part in low for part in LOCKISH)


@dataclass
class Site:
    """One attribute access or call, with the lock set held there."""

    line: int
    end_line: int
    held: Tuple[str, ...]
    method: str
    is_write: bool = False


@dataclass
class ClassModel:
    name: str
    path: str
    line: int
    locks: Dict[str, str] = field(default_factory=dict)  # attr -> canonical attr
    methods: Dict[str, ast.AST] = field(default_factory=dict)
    entries: Set[str] = field(default_factory=set)
    guards: Dict[str, str] = field(default_factory=dict)  # method -> lock id
    # attr -> first __init__ assignment line (annotation anchor)
    decl_lines: Dict[str, int] = field(default_factory=dict)
    accesses: Dict[str, List[Site]] = field(default_factory=dict)
    calls: List[Tuple[ast.Call, Tuple[str, ...], str]] = field(default_factory=list)
    # method -> lock ids it acquires directly (with-regions)
    direct_acquires: Dict[str, Set[str]] = field(default_factory=dict)
    # method -> names of self-methods it calls
    self_calls: Dict[str, Set[str]] = field(default_factory=dict)
    # (outer lock id, inner lock id, line) from lexical nesting
    nest_edges: List[Tuple[str, str, int]] = field(default_factory=list)

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{self.locks.get(attr, attr)}"

    def thread_reachable(self) -> Set[str]:
        seen = set(self.entries)
        frontier = list(seen)
        while frontier:
            m = frontier.pop()
            for callee in self.self_calls.get(m, ()):
                if callee in self.methods and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    def acquire_closure(self, method: str) -> Set[str]:
        out: Set[str] = set()
        seen: Set[str] = set()
        frontier = [method]
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            out |= self.direct_acquires.get(m, set())
            if m in self.guards:
                out.add(self.guards[m])
            frontier.extend(
                c for c in self.self_calls.get(m, ()) if c in self.methods
            )
        return out


@dataclass
class ModuleModel:
    path: str
    classes: Dict[str, ClassModel] = field(default_factory=dict)
    module_locks: Set[str] = field(default_factory=set)
    # module-level functions that are Thread targets
    thread_funcs: Set[str] = field(default_factory=set)
    # calls made inside module-level functions: (func name, callee attr)
    func_calls: Dict[str, Set[str]] = field(default_factory=dict)
    # calls/blocking sites in module functions, with held module locks
    calls: List[Tuple[ast.Call, Tuple[str, ...], str]] = field(default_factory=list)
    nest_edges: List[Tuple[str, str, int]] = field(default_factory=list)
    comments: Dict[int, str] = field(default_factory=dict)


def _lock_ctor_kind(call: ast.Call) -> Optional[str]:
    """'plain'/'condition' when ``call`` constructs a lock, else None."""
    name = _final_name(call.func)
    if name not in LOCK_FACTORIES:
        return None
    return "condition" if name.lower() == "condition" else "plain"


def _shared_lock_arg(call: ast.Call) -> Optional[str]:
    """The ``self.X`` a Condition wraps, if any."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        chain = _chain(arg)
        if len(chain) == 2 and chain[0] == "self":
            return chain[1]
    return None


class _ModelBuilder:
    """Extract a :class:`ModuleModel` from one parsed file."""

    def __init__(self, tree: ast.Module, path: str, comments: Dict[int, str]):
        self.tree = tree
        self.path = path
        self.module = ModuleModel(path=path, comments=comments)
        self.module.func_calls = {}
        self.comments = comments

    def build(self) -> ModuleModel:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if _lock_ctor_kind(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.module.module_locks.add(tgt.id)
            if isinstance(node, ast.ClassDef):
                self._build_class(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_module_func(node)
        self._find_thread_targets()
        self._apply_entry_markers()
        return self.module

    # -- class models ------------------------------------------------------

    def _build_class(self, node: ast.ClassDef) -> None:
        model = ClassModel(name=node.name, path=self.path, line=node.lineno)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                model.methods[item.name] = item
        # pass 1: lock attributes (any method may create them)
        for meth in model.methods.values():
            for sub in ast.walk(meth):
                if not (isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call)):
                    continue
                kind = _lock_ctor_kind(sub.value)
                if not kind:
                    continue
                for tgt in sub.targets:
                    chain = _chain(tgt)
                    if len(chain) == 2 and chain[0] == "self":
                        attr = chain[1]
                        if kind == "condition":
                            shared = _shared_lock_arg(sub.value)
                            model.locks[attr] = shared if shared else attr
                        else:
                            model.locks[attr] = attr
        # resolve conditions wrapping locks declared after them
        for attr, canon in list(model.locks.items()):
            model.locks[attr] = model.locks.get(canon, canon)
        # pass 2: per-method regions, accesses, calls
        for name, meth in model.methods.items():
            guard = self._def_guard(meth, model)
            if guard:
                model.guards[name] = guard
            if name.endswith("_loop"):
                model.entries.add(name)
            if self._def_marker(meth, THREAD_ENTRY):
                model.entries.add(name)
            held0 = (guard,) if guard else ()
            self._walk_exec(meth, list(held0), model, name)
        self.module.classes[node.name] = model

    def _def_marker(self, meth, pattern) -> bool:
        body_start = meth.body[0].lineno if meth.body else meth.lineno
        return any(
            ln in self.comments and pattern.search(self.comments[ln])
            for ln in range(meth.lineno, body_start + 1)
        )

    def _def_guard(self, meth, model: ClassModel) -> Optional[str]:
        body_start = meth.body[0].lineno if meth.body else meth.lineno
        for ln in range(meth.lineno, body_start + 1):
            text = self.comments.get(ln, "")
            m = GUARDED_BY.search(text)
            if m:
                attr = m.group(1).split(".")[-1]
                return f"{model.name}.{model.locks.get(attr, attr)}"
        return None

    def _resolve_lock(self, expr: ast.AST, model: Optional[ClassModel]) -> Optional[str]:
        chain = _chain(expr)
        if not chain:
            return None
        if model is not None and chain[0] == "self" and len(chain) >= 2:
            attr = chain[1]
            if len(chain) == 2 and attr in model.locks:
                return model.lock_id(attr)
            if _is_lockish(chain[-1]):
                return f"{model.name}.{'.'.join(chain[1:])}"
            return None
        if len(chain) == 1:
            name = chain[0]
            if name in self.module.module_locks or _is_lockish(name):
                mod = os.path.splitext(os.path.basename(self.path))[0]
                return f"{mod}.{name}"
        elif _is_lockish(chain[-1]):
            mod = os.path.splitext(os.path.basename(self.path))[0]
            return f"{mod}.{'.'.join(chain)}"
        return None

    def _walk_exec(
        self,
        node: ast.AST,
        held: List[str],
        model: Optional[ClassModel],
        method: str,
    ) -> None:
        """Recursive walk tracking the held-lock stack; records accesses,
        calls, acquisition edges. Nested def/lambda bodies run later on an
        unknown thread — they restart with an empty held set."""
        for child in ast.iter_child_nodes(node):
            self._walk_node(child, held, model, method)

    def _walk_node(
        self,
        child: ast.AST,
        held: List[str],
        model: Optional[ClassModel],
        method: str,
    ) -> None:
        """Dispatch one node: with-statements extend the held stack for
        their body (each body statement dispatched through here again, so
        nested withs stack their edges)."""
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            self._walk_exec(child, [], model, method)
            return
        if isinstance(child, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in child.items:
                lid = self._resolve_lock(item.context_expr, model)
                if lid is None:
                    continue
                if not self._line_marked(child.lineno, LOCK_ORDER_OK):
                    edges = (
                        model.nest_edges if model else self.module.nest_edges
                    )
                    for h in inner:
                        if h != lid:
                            edges.append((h, lid, child.lineno))
                if model:
                    model.direct_acquires.setdefault(method, set()).add(lid)
                if lid not in inner:
                    inner.append(lid)
            for stmt in child.body:
                self._walk_node(stmt, inner, model, method)
            return
        self._record(child, held, model, method)
        self._walk_exec(child, held, model, method)

    def _line_marked(self, line: int, pattern) -> bool:
        text = self.comments.get(line, "")
        return bool(pattern.search(text))

    def _record(
        self, node: ast.AST, held: List[str], model: Optional[ClassModel], method: str
    ) -> None:
        if isinstance(node, ast.Call):
            sink = model.calls if model else self.module.calls
            sink.append((node, tuple(held), method))
            if model is not None:
                chain = _chain(node.func)
                if len(chain) == 2 and chain[0] == "self":
                    model.self_calls.setdefault(method, set()).add(chain[1])
        if model is None:
            return
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            if node.value.id != "self":
                return
            attr = node.attr
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            site = Site(
                line=node.lineno,
                end_line=node.end_lineno or node.lineno,
                held=tuple(held),
                method=method,
                is_write=is_write,
            )
            model.accesses.setdefault(attr, []).append(site)
            if method == "__init__" and is_write and attr not in model.decl_lines:
                model.decl_lines[attr] = node.lineno
        if isinstance(node, ast.Subscript):
            # self.d[k] = v / del self.d[k]: a write to the shared container
            if (
                isinstance(node.ctx, (ast.Store, ast.Del))
                and isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"
            ):
                attr = node.value.attr
                model.accesses.setdefault(attr, []).append(
                    Site(
                        line=node.lineno,
                        end_line=node.end_lineno or node.lineno,
                        held=tuple(held),
                        method=method,
                        is_write=True,
                    )
                )

    # -- module-level thread plumbing -------------------------------------

    def _scan_module_func(self, node) -> None:
        calls: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                calls.add(sub.func.attr)
        self.module.func_calls[node.name] = calls
        self._walk_exec(node, [], None, node.name)

    def _find_thread_targets(self) -> None:
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call) and _final_name(node.func) == "Thread"):
                continue
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None:
                continue
            chain = _chain(target)
            if len(chain) == 2 and chain[0] == "self":
                # attribute entry: credit every class defining the method
                for model in self.module.classes.values():
                    if chain[1] in model.methods:
                        model.entries.add(chain[1])
            elif len(chain) == 1:
                self.module.thread_funcs.add(chain[0])
        # a method called from a module-level thread function runs on that
        # thread (the weakref-trampoline pattern in train/prefetch.py)
        for fn in self.module.thread_funcs:
            for callee in self.module.func_calls.get(fn, ()):
                for model in self.module.classes.values():
                    if callee in model.methods:
                        model.entries.add(callee)

    def _apply_entry_markers(self) -> None:
        pass


# ---------------------------------------------------------------------------
# checks


BLOCKING_SOCKET_ATTRS = ("recv", "recv_into", "sendall", "accept", "connect")
SOCKET_HINTS = ("sock", "conn", "client", "peer", "chan")
THREAD_HINTS = ("thread", "proc", "worker")


def _blocking_what(call: ast.Call) -> Optional[str]:
    """A human-readable label when ``call`` blocks, else None."""
    fn = call.func
    final = _final_name(fn)
    chain = _chain(fn)
    hints = [c.lstrip("_").lower() for c in chain[:-1]] if chain else []
    if final == "sleep":
        return "sleep()"
    if final in ("send_frame", "recv_frame"):
        return f"{final}() frame I/O"
    if final in BLOCKING_SOCKET_ATTRS and isinstance(fn, ast.Attribute):
        return f".{final}() socket op"
    if final == "send" and any(
        any(h in ident for h in SOCKET_HINTS) for ident in hints
    ):
        return ".send() socket op"
    if final == "join" and any(
        any(h in ident for h in THREAD_HINTS) or ident in ("t", "th")
        for ident in hints
    ):
        return ".join() on a thread"
    if final == "request" and any(
        any(h in ident for h in ("client", "rpc", "cli", "router")) for ident in hints
    ):
        return ".request() RPC round-trip"
    if final in ("block_until_ready", "device_get"):
        return f"jax.{final}()"
    return None


class Analyzer:
    """Whole-tree analysis: per-class checks plus the global lock graph."""

    def __init__(self) -> None:
        self.modules: List[ModuleModel] = []
        self.violations: List[Violation] = []
        # lock graph: src -> dst -> (path, line)
        self.edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
        # method name -> [(class model, method)] across all modules
        self.method_index: Dict[str, List[ClassModel]] = {}

    def add_source(self, source: str, path: str) -> None:
        tree = ast.parse(source, filename=path)
        comments = comment_lines(source)
        module = _ModelBuilder(tree, path, comments).build()
        self.modules.append(module)
        for model in module.classes.values():
            for m in model.methods:
                self.method_index.setdefault(m, []).append(model)

    # -- suppression helpers ----------------------------------------------

    def _marker(self, module: ModuleModel, lines, pattern) -> Optional[str]:
        """The marker reason when any of ``lines`` carries ``pattern``;
        None when absent. An empty reason returns '' (and is a violation
        at the call sites that require justification)."""
        if isinstance(lines, int):
            lines = range(lines, lines + 1)
        for ln in lines:
            text = module.comments.get(ln, "")
            m = pattern.search(text)
            if m:
                return _strip_reason(m.group(1)) if m.groups() else ""
        return None

    def _suppressed(
        self, module: ModuleModel, lines, pattern, label: str
    ) -> Optional[bool]:
        """True → suppressed with reason; False → no marker; emitting a
        violation (and returning True, site handled) for a reason-less
        marker."""
        reason = self._marker(module, lines, pattern)
        if reason is None:
            return False
        if not reason:
            first = lines if isinstance(lines, int) else lines[0]
            self.violations.append(
                Violation(
                    module.path,
                    first,
                    f"'{label}' suppression without a reason — every "
                    "suppression must name its justification",
                )
            )
        return True

    # -- check 1: unguarded shared state ----------------------------------

    def _check_shared_state(self, module: ModuleModel, model: ClassModel) -> None:
        if not model.entries:
            return
        reachable = model.thread_reachable()
        class_locks = {model.lock_id(a) for a in model.locks}
        for attr, sites in sorted(model.accesses.items()):
            if attr in model.locks:
                continue
            thread_writes = [
                s
                for s in sites
                if s.is_write
                and s.method in reachable
                and s.method not in CONSTRUCTORS
            ]
            outside = [
                s
                for s in sites
                if s.method not in reachable and s.method not in CONSTRUCTORS
            ]
            if not thread_writes or not outside:
                continue
            decl = model.decl_lines.get(attr)
            decl_lines = range(decl, decl + 1) if decl else range(0)
            # attribute-level escape hatches on the __init__ assignment line
            if self._suppressed(module, list(decl_lines) or 0, RACE_OK, "race: ok") and decl:
                continue
            guard = self._marker(module, list(decl_lines) or 0, GUARDED_BY) if decl else None
            guard_id = None
            if guard is not None:
                attr_name = guard.split(".")[-1]
                if attr_name in model.locks:
                    guard_id = model.lock_id(attr_name)
                else:
                    # external mechanism (queue-internal, GIL, …): trusted
                    continue
            required = {guard_id} if guard_id else class_locks
            for s in sites:
                if s.method in CONSTRUCTORS:
                    continue
                if set(s.held) & required:
                    continue
                span = list(range(s.line, s.end_line + 1))
                if self._suppressed(module, span, RACE_OK, "race: ok"):
                    continue
                if self._marker(module, span, GUARDED_BY) is not None:
                    # site-level assertion: protected by a mechanism the
                    # analyzer cannot see (trusted, but documented)
                    continue
                want = (
                    f"under {guard_id}" if guard_id else "under the class lock"
                )
                kind = "written" if s.is_write else "read"
                self.violations.append(
                    Violation(
                        module.path,
                        s.line,
                        f"{model.name}.{attr} {kind} in {s.method}() without "
                        f"holding a lock, but a thread entry point writes it "
                        f"— access it {want}, declare '# guarded-by: <lock>', "
                        "or justify '# race: ok — <reason>'",
                    )
                )

    # -- check 2: lock-order graph ----------------------------------------

    def _add_edge(self, src: str, dst: str, path: str, line: int) -> None:
        if src == dst:
            return
        self.edges.setdefault(src, {}).setdefault(dst, (path, line))

    def _collect_edges(self, module: ModuleModel) -> None:
        for src, dst, line in module.nest_edges:
            self._add_edge(src, dst, module.path, line)
        for model in module.classes.values():
            for src, dst, line in model.nest_edges:
                self._add_edge(src, dst, module.path, line)
            for call, held, method in model.calls:
                if not held:
                    continue
                if self._marker(module, call.lineno, LOCK_ORDER_OK) is not None:
                    continue
                chain = _chain(call.func)
                if not chain or not isinstance(call.func, ast.Attribute):
                    continue
                callee = chain[-1]
                hints = [c.lstrip("_").lower() for c in chain[:-1]]
                if chain[0] == "self" and len(chain) == 2:
                    targets = [model] if callee in model.methods else []
                else:
                    targets = [
                        other
                        for other in self.method_index.get(callee, ())
                        if other is not model
                        and self._hints_match(hints, other.name)
                    ]
                for target in targets:
                    for lid in target.acquire_closure(callee):
                        for h in held:
                            self._add_edge(h, lid, module.path, call.lineno)

    @staticmethod
    def _hints_match(hints: List[str], class_name: str) -> bool:
        cls = class_name.lower()
        for ident in hints:
            if ident in ("self", "cls") or len(ident) < 3:
                continue
            if ident in cls or cls in ident:
                return True
        return False

    def _check_cycles(self) -> None:
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(node: str) -> Optional[List[str]]:
            color[node] = 1
            stack.append(node)
            for nxt in sorted(self.edges.get(node, ())):
                if color.get(nxt, 0) == 1:
                    return stack[stack.index(nxt):] + [nxt]
                if color.get(nxt, 0) == 0:
                    cycle = dfs(nxt)
                    if cycle:
                        return cycle
            stack.pop()
            color[node] = 2
            return None

        for node in sorted(self.edges):
            if color.get(node, 0) == 0:
                cycle = dfs(node)
                if cycle:
                    path, line = self.edges[cycle[0]][cycle[1]]
                    self.violations.append(
                        Violation(
                            path,
                            line,
                            "lock-order cycle: " + " -> ".join(cycle) + " — "
                            "break the inversion or justify the edge with "
                            "'# lock-order: ok — <reason>'",
                        )
                    )
                    return

    # -- check 3: blocking under lock -------------------------------------

    def _check_blocking(self, module: ModuleModel) -> None:
        pools = [(None, module.calls)] + [
            (model, model.calls) for model in module.classes.values()
        ]
        for _model, calls in pools:
            for call, held, _method in calls:
                if not held:
                    continue
                what = _blocking_what(call)
                if what is None:
                    continue
                span = list(range(call.lineno, (call.end_lineno or call.lineno) + 1))
                if self._suppressed(module, span, BLOCKING_OK, "blocking: ok"):
                    continue
                self.violations.append(
                    Violation(
                        module.path,
                        call.lineno,
                        f"{what} while holding {', '.join(held)} — move the "
                        "blocking call outside the lock or justify "
                        "'# blocking: ok — <reason>'",
                    )
                )

    # -- required models ---------------------------------------------------

    def _check_required(self) -> None:
        for suffix, cls, lock in REQUIRED_MODELS:
            found = False
            for module in self.modules:
                if not module.path.endswith(suffix):
                    continue
                model = module.classes.get(cls)
                if (
                    model is not None
                    and lock in model.locks
                    and model.entries
                ):
                    found = True
                break
            else:
                continue  # tree does not contain the file: not required
            if not found:
                self.violations.append(
                    Violation(
                        suffix,
                        0,
                        f"required concurrency model missing: {cls} in "
                        f"{suffix} must keep its {lock!r} lock and a thread "
                        "entry point — the lock discipline lost its lint "
                        "protection",
                    )
                )

    # -- driver ------------------------------------------------------------

    def run(self, required: bool = True) -> List[Violation]:
        for module in self.modules:
            for model in module.classes.values():
                self._check_shared_state(module, model)
            self._check_blocking(module)
            self._collect_edges(module)
        self._check_cycles()
        if required:
            self._check_required()
        self.violations.sort(key=lambda v: (v.path, v.line))
        return self.violations


def find_violations(source: str, path: str) -> List[Tuple[int, str]]:
    """Single-source entry (fixture tests): all three checks over one file,
    without the REQUIRED_MODELS presence check."""
    analyzer = Analyzer()
    analyzer.add_source(source, path)
    return [(v.line, v.what) for v in analyzer.run(required=False)]


def check_tree(root: str) -> List[Tuple[str, int, str]]:
    analyzer = Analyzer()
    violations: List[Violation] = []
    for path in iter_py_files(root):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        try:
            analyzer.add_source(source, path)
        except SyntaxError as e:
            violations.append(Violation(path, e.lineno or 0, f"syntax error: {e.msg}"))
    violations.extend(analyzer.run())
    return violations


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.join(repo_root(), "maggy_tpu")
    return report(check_tree(root))


if __name__ == "__main__":
    sys.exit(main())
